"""HybridParallelOptimizer — optimizer wrapper for hybrid-parallel training.

Ref: fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py
(upstream layout, unverified — mount empty). Paddle's version re-implements
global-norm grad clip across the dp/mp/pp/sharding meshes (NCCL allreduces of
the squared norm) and fuses the DP allreduce. Under GSPMD the DP grad psum is
inside the jitted step, and eager grads are GLOBAL jax.Arrays — plain jnp
reductions over them already produce the cross-mesh value. The part that
still needs real logic is the clip itself: when called inside shard_map
(per-shard local views), the squared norm of tensor-parallel-sharded params
must be psum'd over the model-parallel axis while replicated params are
counted once. HybridParallelClipGrad implements exactly that split (keyed by
Parameter.is_distributed, as paddle keys it), and HybridParallelOptimizer
swaps it in for a plain ClipGradByGlobalNorm — same substitution paddle's
wrapper performs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor
from ....nn.clip import ClipGradByGlobalNorm
from ...communication import _axis_in_scope

__all__ = ["HybridParallelOptimizer", "HybridParallelClipGrad"]


class HybridParallelClipGrad:
    """Global-norm clip that is correct in both execution regimes:

    - eager / GSPMD arrays: every grad is a global array; one plain reduction
      covers dp/mp/pp/sharding at once (XLA inserts the collectives);
    - inside shard_map (per-shard views): the squared norm of distributed
      (TP-sharded) params is psum'd over the mp axis; replicated params are
      counted once, NOT multiplied by the mp degree.
    """

    def __init__(self, clip: ClipGradByGlobalNorm, hcg=None):
        self._clip = clip
        self._hcg = hcg
        self.clip_norm = clip.clip_norm

    @staticmethod
    def _sq(g):
        return jnp.sum(jnp.square(g.astype(jnp.float32)))

    def _global_norm(self, dist_datas, repl_datas):
        dist_sq = sum((self._sq(d) for d in dist_datas),
                      jnp.zeros((), jnp.float32))
        repl_sq = sum((self._sq(d) for d in repl_datas),
                      jnp.zeros((), jnp.float32))
        if dist_datas and _axis_in_scope("mp"):
            # per-shard views: each mp rank holds a slice of the sharded
            # params — sum their contributions
            dist_sq = jax.lax.psum(dist_sq, "mp")
        return jnp.sqrt(dist_sq + repl_sq)

    def __call__(self, params_grads):
        clippable = [(p, g) for p, g in params_grads
                     if g is not None and getattr(p, "need_clip", True)]
        if not clippable:
            return params_grads
        gnorm = self._global_norm(
            [g._data for p, g in clippable
             if getattr(p, "is_distributed", False)],
            [g._data for p, g in clippable
             if not getattr(p, "is_distributed", False)])
        factor = jnp.minimum(self.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, Tensor((g._data * factor).astype(
                    g._data.dtype), stop_gradient=True)))
        return out

    def _clip_fn(self):
        """Pure pytree form for jitted steps (global GSPMD arrays)."""
        return self._clip._clip_fn()


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        # paddle substitution: a plain global-norm clip becomes the
        # mesh-aware hybrid clip
        inner_clip = getattr(optimizer, "_grad_clip", None)
        if isinstance(inner_clip, ClipGradByGlobalNorm) and not isinstance(
                inner_clip, HybridParallelClipGrad):
            optimizer._grad_clip = HybridParallelClipGrad(inner_clip, hcg)

    @property
    def inner_opt(self):
        return self._inner_opt

    # delegate the full Optimizer surface
    def __getattr__(self, name):
        return getattr(self._inner_opt, name)

    def step(self):
        return self._inner_opt.step()

    def clear_grad(self, *a, **k):
        return self._inner_opt.clear_grad(*a, **k)

    def minimize(self, *a, **k):
        from ....static.program import default_main_program, in_static_mode

        if in_static_mode():
            # the static meta-optimizer seam: record the hybrid context on
            # the Program so the Executor compiles the fleet path (GSPMD TP
            # shardings + pipeline segmentation — static/fleet_pass.py)
            program = default_main_program()
            mesh = getattr(self._hcg, "mesh", None) if self._hcg else None
            program._dist_context = {"mesh": mesh,
                                     "strategy": self._strategy}
        return self._inner_opt.minimize(*a, **k)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def functional_state(self, params):
        return self._inner_opt.functional_state(params)

    def functional_step(self, *a, **k):
        return self._inner_opt.functional_step(*a, **k)

    # ------------------------------------------ sharded-dp (ZeRO) bridge
    def zero_train_step(self, model, loss_fn=None, *, stage=None, **kwargs):
        """fleet.distributed_optimizer's rebinding onto the
        `paddle_tpu.parallel.zero` engine (ISSUE 16): build the explicit
        shard_map ZeRO step at dp = the hcg's sharding (or data) parallel
        degree. `stage` defaults to 1 (ZeRO-1) when the strategy enables
        sharding, else 0 (plain replicated dp on the same substrate)."""
        from ....parallel.zero import ZeroTrainStep

        dp = 1
        if self._hcg is not None:
            sharding = self._hcg.get_sharding_parallel_world_size()
            dp = sharding if sharding > 1 else \
                self._hcg.get_data_parallel_world_size()
            if stage is None:
                stage = 1 if sharding > 1 else 0
        return ZeroTrainStep(model, self._inner_opt, loss_fn,
                             dp=max(int(dp), 1),
                             stage=1 if stage is None else int(stage),
                             **kwargs)
