"""paddle.version analog (ref: python/paddle/version.py, generated at
build time upstream; here static for the TPU-native build)."""
full_version = "0.3.0"  # == paddle.__version__
major = "0"
minor = "3"
patch = "0"
rc = "0"
commit = "tpu-native"
istaged = False
with_pip = False
cuda_version = "False"      # upstream reports the CUDA toolkit; TPU build
cudnn_version = "False"
xpu_version = "False"
tensorrt_version = "False"


def show():
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")
    print("backend: jax/XLA (TPU-native)")


def cuda():
    return False


def cudnn():
    return False
