"""Replicated serving cluster (ISSUE 9): `ServingCluster` over N
supervised engine replicas. Router: load-aware placement, prefix
affinity, round-robin, spill-over on `EngineOverloaded` before the
caller ever sees it. Health: degraded on supervisor restarts / fault
bursts, healed after clean steps, drain/resume, `max_dead_replicas`.
Failover: THE acceptance criterion is replica-loss parity — three
replicas, a seeded `device_lost` kill of one mid-run, and every
request (including the migrated ones) completes with a token stream
bit-identical to an uninterrupted single-engine run, exactly-once
across `stream()` consumers, for greedy AND seeded-stochastic sampling
at decode horizons 1 and 8. The chaos matrix varies the kill site
(mid-prefill, mid-horizon, victim holding shared prefix pages) and the
routing mode. Hedged re-dispatch races a stuck request's clone against
the original (winner-agnostic assertions: exactly one survivor, zero
duplicate tokens, bit-identical output). The zero-cost guard pins that
a single-engine serve path executes NO cluster code.

Single tiny LLaMA reused module-wide (tests/test_serving.py's pattern);
every replica shares the model's memoized jit cache, so the matrix
compiles one prefill-bucket + decode set.
"""
import functools

import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import (
    EngineDead, EngineOverloaded, FaultInjector, RequestJournal,
    ServingCluster, ServingEngine,
)


@functools.lru_cache(maxsize=None)
def _llama():
    paddle.seed(1234)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m


_ENGINE_KW = dict(page_size=4, num_pages=64, max_batch_size=4,
                  max_seq_len=64, decode_horizon=4, retry_backoff_s=0.0)


def _factory(**overrides):
    kw = dict(_ENGINE_KW, **overrides)

    def make(replica=None, fault_injector=None):
        return ServingEngine(_llama(), fault_injector=fault_injector,
                             **kw)
    return make


def _engine(**overrides):
    return ServingEngine(_llama(), **dict(_ENGINE_KW, **overrides))


_PROMPTS = [[7, 3, 9, 1, 4], [2, 8, 6, 5, 1, 9, 3, 7, 2],
            [4, 4, 1, 8, 8, 2, 6, 3, 9, 5, 1, 7, 3]]

# two-page shared system prompt (page_size=4) so affinity/shared-prefix
# configs actually share cached pages
_SHARED = [6, 1, 6, 1, 8, 0, 3, 3]
_SHARED_PROMPTS = [_SHARED + [7, 3, 9], _SHARED + [2, 8, 6, 5, 1],
                   _SHARED + [4, 4, 1, 8, 8, 2, 6]]


def _sampling_kw(i, seeded):
    return (dict(temperature=0.8, top_k=5, seed=100 + i) if seeded
            else dict(seed=7))


def _reference(prompts, seeded=False, max_new_tokens=6, **engine_kw):
    """Fault-free single-engine run: the parity oracle."""
    eng = _engine(**engine_kw)
    rids = [eng.add_request(p, max_new_tokens=max_new_tokens,
                            **_sampling_kw(i, seeded))
            for i, p in enumerate(prompts)]
    out = eng.run()
    return [out[r] for r in rids]


# ------------------------------------------------------------- routing

class TestRouting:
    def test_load_placement_spreads_requests(self):
        cl = ServingCluster(_factory(), num_replicas=2)
        for p in _PROMPTS:
            cl.add_request(p, max_new_tokens=4, seed=7)
        routed = cl.stats()["router"]["routed"]
        assert sum(routed) == 3 and all(n > 0 for n in routed)

    def test_round_robin_rotates(self):
        cl = ServingCluster(_factory(), num_replicas=3,
                            placement="round_robin",
                            prefix_affinity=False)
        for p in _PROMPTS:
            cl.add_request(p, max_new_tokens=4, seed=7)
        assert cl.stats()["router"]["routed"] == [1, 1, 1]

    def test_prefix_affinity_steers_shared_prompts_together(self):
        cl = ServingCluster(_factory(enable_prefix_caching=True),
                            num_replicas=3)
        first = cl.add_request(_SHARED_PROMPTS[0], max_new_tokens=4,
                               seed=7)
        home = cl._records[first].replica
        # prefill so the shared pages actually enter r<home>'s cache
        out = cl.run()
        assert len(out[first]) == len(_SHARED_PROMPTS[0]) + 4
        for p in _SHARED_PROMPTS[1:]:
            rid = cl.add_request(p, max_new_tokens=4, seed=7)
            assert cl._records[rid].replica == home
        assert cl.stats()["router"]["affinity_hits"] >= 2

    def test_affinity_disabled_ignores_prefix(self):
        cl = ServingCluster(_factory(enable_prefix_caching=True),
                            num_replicas=2, prefix_affinity=False)
        for p in _SHARED_PROMPTS:
            cl.add_request(p, max_new_tokens=4, seed=7)
        st = cl.stats()["router"]
        assert st["affinity_hits"] == 0 and st["affinity_misses"] == 0
        assert st["affinity_table"] == 0

    def test_spillover_then_shed(self):
        # each replica holds at most one waiting request; the third
        # admission spills off the full first choice onto the second
        # replica, the fifth finds everyone full and sheds
        cl = ServingCluster(_factory(max_waiting=2, max_batch_size=1),
                            num_replicas=2, prefix_affinity=False)
        for k in range(4):
            cl.add_request(_PROMPTS[k % 3], max_new_tokens=2, seed=7)
        with pytest.raises(EngineOverloaded):
            cl.add_request(_PROMPTS[0], max_new_tokens=2, seed=7)
        st = cl.stats()["router"]
        assert st["routed"] == [2, 2]
        assert st["spillovers"] >= 1 and st["shed"] == 1

    def test_unknown_placement_rejected(self):
        with pytest.raises(ValueError, match="placement"):
            ServingCluster(_factory(), placement="bogus")


# ----------------------------------------------------- single-API parity

class TestClusterParity:
    @pytest.mark.parametrize("seeded", [False, True])
    def test_matches_single_engine(self, seeded):
        want = _reference(_PROMPTS, seeded=seeded)
        cl = ServingCluster(_factory(), num_replicas=2)
        rids = [cl.add_request(p, max_new_tokens=6,
                               **_sampling_kw(i, seeded))
                for i, p in enumerate(_PROMPTS)]
        out = cl.run()
        assert [out[r] for r in rids] == want
        assert all(cl.status(r) == ("finished", None) for r in rids)
        assert cl.check_consistency()

    def test_stream_exactly_once_with_done_flags(self):
        cl = ServingCluster(_factory(), num_replicas=2)
        rids = [cl.add_request(p, max_new_tokens=5, seed=7)
                for p in _PROMPTS]
        seen, done_for = {}, set()
        for rid, tok, done in cl.stream():
            seen.setdefault(rid, []).append(tok)
            if done:
                done_for.add(rid)
        assert done_for == set(rids)
        for rid in rids:
            assert cl.output(rid) == \
                list(cl._records[rid].prompt) + seen[rid]
            assert len(seen[rid]) == 5

    def test_cancel_and_status(self):
        cl = ServingCluster(_factory(), num_replicas=2)
        rid = cl.add_request(_PROMPTS[0], max_new_tokens=8, seed=7)
        assert cl.status(rid)[0] == "waiting"
        assert cl.cancel(rid) is True
        assert cl.cancel(rid) is False
        assert cl.status(rid) == ("cancelled", None)
        cl.run()
        assert cl.status(rid) == ("cancelled", None)
        with pytest.raises(KeyError):
            cl.status(12345)


# ------------------------------------------------------ health lifecycle

class TestHealth:
    def test_drain_resume_routing(self):
        cl = ServingCluster(_factory(), num_replicas=2)
        cl.drain(0)
        assert cl.health() == ["draining", "healthy"]
        for p in _PROMPTS:
            cl.add_request(p, max_new_tokens=2, seed=7)
        assert cl.stats()["router"]["routed"] == [0, 3]
        cl.resume(0)
        assert cl.health() == ["healthy", "healthy"]
        cl.drain(0)
        cl.drain(1)
        with pytest.raises(EngineOverloaded, match="no placeable"):
            cl.add_request(_PROMPTS[0], max_new_tokens=2, seed=7)

    def test_fault_burst_degrades_then_heals(self):
        inj = [FaultInjector().fail_at("dispatch", 1, transient=True),
               FaultInjector()]
        cl = ServingCluster(_factory(), num_replicas=2,
                            fault_injectors=inj,
                            degrade_after_faults=1,
                            degrade_recovery_steps=2)
        # both replicas busy so maintenance keeps running after the fault
        rids = [cl.add_request(p, max_new_tokens=10, seed=7)
                for p in _PROMPTS]
        states = set()
        while cl.has_work():
            cl.step()
            states.add(cl.health()[0])
        assert "degraded" in states        # the burst tripped it
        assert cl.health()[0] == "healthy"  # ...and clean steps healed it
        # the transient fault cost latency, never a token
        out = {r: cl.output(r) for r in rids}
        want = _reference(_PROMPTS, max_new_tokens=10)
        assert [out[r] for r in rids] == want

    def test_restart_marks_degraded(self):
        inj = [FaultInjector().fail_at("device_lost", 1),
               FaultInjector()]
        cl = ServingCluster(_factory(), num_replicas=2,
                            fault_injectors=inj,
                            degrade_recovery_steps=10 ** 6)
        for p in _PROMPTS:
            cl.add_request(p, max_new_tokens=6, seed=7)
        cl.run()
        assert cl.health()[0] == "degraded"
        assert len(cl.replicas[0].supervisor.restarts) == 1

    def test_max_dead_replicas_raises(self):
        inj = [FaultInjector().fail_at("device_lost", 1),
               FaultInjector()]
        cl = ServingCluster(_factory(), num_replicas=2,
                            fault_injectors=inj,
                            supervisor_kw=dict(max_restarts=0),
                            max_dead_replicas=0)
        for p in _PROMPTS:
            cl.add_request(p, max_new_tokens=6, seed=7)
        with pytest.raises(EngineDead, match="max_dead_replicas"):
            cl.run()


# ----------------------------------------------- replica-loss acceptance

class TestReplicaLossParity:
    """THE acceptance criterion: kill one of three replicas mid-run and
    every request — including the ones migrated off the corpse —
    completes bit-identical to an uninterrupted single-engine run,
    exactly-once across the stream, journal + scheduler invariants clean
    on every survivor."""

    @pytest.mark.parametrize("horizon", [1, 8])
    @pytest.mark.parametrize("seeded", [False, True])
    def test_kill_one_replica_bit_identical(self, horizon, seeded):
        want = _reference(_PROMPTS, seeded=seeded,
                          decode_horizon=horizon)
        inj = [FaultInjector(),
               FaultInjector().fail_at("device_lost", 2),
               FaultInjector()]
        cl = ServingCluster(_factory(decode_horizon=horizon),
                            num_replicas=3, fault_injectors=inj,
                            supervisor_kw=dict(max_restarts=0))
        rids = [cl.add_request(p, max_new_tokens=6,
                               **_sampling_kw(i, seeded))
                for i, p in enumerate(_PROMPTS)]
        seen = {}
        for rid, tok, done in cl.stream():
            seen.setdefault(rid, []).append(tok)
        assert cl.health().count("dead") == 1
        out = {r: cl.output(r) for r in rids}
        assert [out[r] for r in rids] == want
        for i, rid in enumerate(rids):      # stream == output, no dup/lost
            assert seen[rid] == out[rid][len(_PROMPTS[i]):]
        assert cl.check_consistency()
        st = cl.stats()
        assert st["replica_deaths"] == 1
        assert st["num_finished"] == len(rids)

    def test_double_death_chained_migration(self):
        """A migrated request's new home dying too re-migrates it from
        the full-history record the first migration registered."""
        want = _reference(_PROMPTS, max_new_tokens=8)
        inj = [FaultInjector().fail_at("device_lost", 1),
               FaultInjector().fail_at("device_lost", 3),
               FaultInjector()]
        cl = ServingCluster(_factory(), num_replicas=3,
                            fault_injectors=inj, prefix_affinity=False,
                            supervisor_kw=dict(max_restarts=0))
        rids = [cl.add_request(p, max_new_tokens=8, seed=7)
                for p in _PROMPTS]
        out = cl.run()
        assert cl.health().count("dead") == 2
        assert [out[r] for r in rids] == want
        assert cl.check_consistency()

    def test_dead_replica_unroutable_and_tagged_in_stats(self):
        inj = [FaultInjector().fail_at("device_lost", 1),
               FaultInjector()]
        cl = ServingCluster(_factory(), num_replicas=2,
                            fault_injectors=inj,
                            supervisor_kw=dict(max_restarts=0))
        rids = [cl.add_request(p, max_new_tokens=6, seed=7)
                for p in _PROMPTS]
        cl.run()
        assert cl.health()[0] == "dead"
        rid = cl.add_request(_PROMPTS[0], max_new_tokens=2, seed=7)
        assert cl._records[rid].replica == 1
        with pytest.raises(ValueError, match="dead"):
            cl.drain(0)
        st = cl.stats()
        assert st["dead_replicas"] == 1
        assert st["replicas"][0]["stats"]["dead"] is True
        assert all(cl.status(r)[0] == "finished" for r in rids)


# ------------------------------------------------------------ chaos matrix

_CHAOS_MODES = [("load", True), ("round_robin", False)]


class TestClusterChaosMatrix:
    """Seeded kills at every interesting site × routing modes: survivors
    bit-identical to a fault-free single-engine run, zero duplicated or
    lost tokens, per-replica invariants clean after every migration."""

    @pytest.mark.parametrize("placement,affinity", _CHAOS_MODES)
    @pytest.mark.parametrize("kill_at", [0, 2])
    def test_kill_anywhere(self, placement, affinity, kill_at):
        # kill_at=0 dies on its very first step (mid-prefill: nothing
        # delivered yet); kill_at=2 mid-decode with horizon partials
        want = _reference(_PROMPTS, max_new_tokens=6)
        injectors = [FaultInjector() for _ in range(3)]
        injectors[1].fail_at("device_lost", kill_at)
        cl = ServingCluster(_factory(), num_replicas=3,
                            placement=placement,
                            prefix_affinity=affinity,
                            fault_injectors=injectors,
                            supervisor_kw=dict(max_restarts=0))
        rids = [cl.add_request(p, max_new_tokens=6, seed=7)
                for p in _PROMPTS]
        seen = {}
        for rid, tok, done in cl.stream():
            seen.setdefault(rid, []).append(tok)
        out = {r: cl.output(r) for r in rids}
        assert [out[r] for r in rids] == want
        for i, rid in enumerate(rids):
            assert seen.get(rid, []) == out[rid][len(_PROMPTS[i]):]
        assert cl.check_consistency()

    def test_kill_replica_holding_shared_prefix_pages(self):
        """Affinity packs the shared-prefix requests onto one replica;
        killing exactly that replica migrates all of them at once —
        folded re-prefills on survivors whose caches never saw the
        prefix — still bit-identical."""
        want = _reference(_SHARED_PROMPTS, max_new_tokens=6,
                          enable_prefix_caching=True)
        injectors = [FaultInjector() for _ in range(3)]
        cl = ServingCluster(_factory(enable_prefix_caching=True),
                            num_replicas=3, fault_injectors=injectors,
                            supervisor_kw=dict(max_restarts=0))
        rids = [cl.add_request(_SHARED_PROMPTS[0], max_new_tokens=6,
                               seed=7)]
        victim = cl._records[rids[0]].replica
        cl.run()                          # prefix pages now cached there
        rids += [cl.add_request(p, max_new_tokens=6, seed=7)
                 for p in _SHARED_PROMPTS[1:]]
        # affinity pulled every shared prompt onto the same replica
        assert all(cl._records[r].replica == victim for r in rids)
        injectors[victim].fail_at(
            "device_lost",
            injectors[victim].counts.get("device_lost", 0) + 1)
        out = cl.run()
        assert cl.health()[victim] == "dead"
        assert [out[r] for r in rids] == want
        assert cl.stats()["migrations"] >= 1
        assert cl.check_consistency()

    @pytest.mark.slow
    @pytest.mark.parametrize("chaos_seed", [11, 23])
    def test_seeded_cluster_chaos_deterministic(self, chaos_seed):
        """One integer drives every replica's injector; two clusters
        built from the same seed take identical fault schedules and
        produce identical outputs (and both match the oracle)."""
        want = _reference(_PROMPTS, max_new_tokens=6)

        def run_once():
            cl = ServingCluster(_factory(), num_replicas=3,
                                chaos_seed=chaos_seed,
                                supervisor_kw=dict(max_restarts=1))
            for inj in cl.fault_injectors:
                inj.fail_rate("dispatch", 0.05)
            rids = [cl.add_request(p, max_new_tokens=6, seed=7)
                    for p in _PROMPTS]
            out = cl.run()
            fired = [dict(i.fired) for i in cl.fault_injectors]
            return [out[r] for r in rids], fired

        out_a, fired_a = run_once()
        out_b, fired_b = run_once()
        assert out_a == out_b == want
        assert fired_a == fired_b


# -------------------------------------------------------------- hedging

class TestHedging:
    def _stuck_cluster(self, tick):
        """2 replicas, r0 degraded and the fake clock far past
        `hedge_after_s`: the next step MUST hedge r0's request onto r1.
        Winner-agnostic from here on — both copies race."""
        cl = ServingCluster(_factory(), num_replicas=2,
                            hedge_after_s=5.0,
                            clock=lambda: tick[0])
        cl.drain(1)                       # force placement onto r0
        rid = cl.add_request(_PROMPTS[0], max_new_tokens=6, seed=7)
        cl.resume(1)
        cl._set_health(cl.replicas[0], "degraded")
        tick[0] += 100.0                  # way past the hedge deadline
        return cl, rid

    def test_hedge_fires_and_consumer_sees_one_stream(self):
        want = _reference(_PROMPTS[:1], max_new_tokens=6)[0]
        tick = [0.0]
        cl, rid = self._stuck_cluster(tick)
        seen = []
        for r, tok, done in cl.stream():
            assert r == rid               # the clone never leaks its id
            seen.append(tok)
        assert cl.stats()["hedges"] == 1
        assert cl.stats()["hedge_cancels"] == 1
        assert cl.output(rid) == want     # bit-identical, zero dups
        assert seen == want[len(_PROMPTS[0]):]
        assert len(cl._records[rid].copies) <= 1
        assert cl.status(rid) == ("finished", None)
        assert cl.check_consistency()

    def test_hedge_then_owner_death_survivor_owns_stream(self):
        """The original's replica dies after the hedge: the clone is
        the surviving copy and the migration path hands it the stream
        instead of re-admitting anything."""
        want = _reference(_PROMPTS[:1], max_new_tokens=6)[0]
        tick = [0.0]
        cl = ServingCluster(_factory(), num_replicas=2,
                            hedge_after_s=5.0,
                            supervisor_kw=dict(max_restarts=0),
                            clock=lambda: tick[0])
        cl.drain(1)
        rid = cl.add_request(_PROMPTS[0], max_new_tokens=6, seed=7)
        cl.resume(1)
        cl._set_health(cl.replicas[0], "degraded")
        # r0 dies on its NEXT step — the same step whose maintenance
        # phase plants the hedge on r1
        cl.replicas[0].injector = None    # (not used; death via below)
        inj = FaultInjector().fail_at("device_lost", 0)
        cl.replicas[0].supervisor.engine._faults = inj
        tick[0] += 100.0
        out = cl.run()
        assert cl.health() == ["dead", "healthy"]
        assert out[rid] == want
        assert cl.stats()["hedges"] == 1
        assert cl.stats()["migrations"] == 0   # survivor, not re-admit
        assert cl.status(rid) == ("finished", None)
        assert cl.check_consistency()

    def test_no_hedge_when_disabled_or_healthy(self):
        tick = [0.0]
        cl = ServingCluster(_factory(), num_replicas=2,
                            hedge_after_s=5.0, clock=lambda: tick[0])
        cl.add_request(_PROMPTS[0], max_new_tokens=4, seed=7)
        tick[0] += 100.0                  # stale but owner is healthy
        cl.run()
        assert cl.stats()["hedges"] == 0


# ------------------------------------------------------ zero-cost guard

class TestZeroCostWhenUnused:
    def test_single_engine_path_executes_no_cluster_code(self,
                                                         monkeypatch):
        """An engine + supervisor serve (journal attached, faults
        injected and recovered — the full PR-7 surface) must execute
        ZERO new code: every cluster entry point, the engine's adopt
        path, the cache's peek probe and the journal's adopt are
        booby-trapped."""
        def boom(*a, **k):
            raise AssertionError("cluster code on single-engine path")

        import paddle_tpu.serving.cluster as cluster_mod
        from paddle_tpu.serving import EngineSupervisor, PrefixCache
        for name in ("add_request", "step", "stream", "run", "cancel",
                     "status", "output", "stats", "drain", "resume",
                     "_candidates", "_ingest", "_maintenance", "_hedge",
                     "_on_replica_death", "_migrate_one", "_adopt_on",
                     "_affinity_keys", "_load_score", "chaos_injectors"):
            monkeypatch.setattr(cluster_mod.ServingCluster, name, boom)
        monkeypatch.setattr(ServingEngine, "adopt_request", boom)
        monkeypatch.setattr(PrefixCache, "peek", boom)
        monkeypatch.setattr(RequestJournal, "adopt", boom)

        inj = FaultInjector().fail_at("device_lost", 1)
        sup = EngineSupervisor(
            lambda: _engine(enable_prefix_caching=True,
                            fault_injector=inj),
            journal=RequestJournal())
        rids = [sup.add_request(p, max_new_tokens=4, seed=7)
                for p in _SHARED_PROMPTS]
        out = sup.run()
        assert len(sup.restarts) == 1     # the recovery path DID run
        for i, rid in enumerate(rids):
            assert len(out[rid]) == len(_SHARED_PROMPTS[i]) + 4
