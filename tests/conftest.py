"""Test harness config.

Mirrors the reference's single-host multi-device emulation (SURVEY.md §4):
8 fake devices on CPU via xla_force_host_platform_device_count so every
mesh/collective/parallelism test runs hermetically without TPU hardware.
Must run before jax is first imported.
"""
import os

from _device_env import ensure_fake_devices

# PADDLE_TPU_TEST_PLATFORM=tpu runs the suite on real hardware instead of
# the hermetic 8-fake-device CPU default. The axon sitecustomize pins
# jax_platforms at interpreter start; ensure_fake_devices selects the
# backend via config before any backend is initialized ("axon" skips the
# pin; non-cpu platforms skip the fake-device flag).
_plat = os.environ.get("PADDLE_TPU_TEST_PLATFORM", "cpu")
ensure_fake_devices(8 if _plat == "cpu" else None,
                    platform=None if _plat == "axon" else _plat)

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import jax  # noqa: E402

# full fp32 matmuls for numeric comparisons (TPU bench keeps its own default)
jax.config.update("jax_default_matmul_precision", "highest")


def pytest_configure(config):
    # the tier-1 fast lane runs `-m 'not slow'`; anything that compiles
    # beyond a module's core executable set carries this marker
    config.addinivalue_line(
        "markers", "slow: heavy test excluded from the tier-1 fast lane")


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True)
def _seed_framework():
    import paddle_tpu as paddle

    paddle.seed(1234)
    yield
