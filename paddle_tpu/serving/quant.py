"""Quantized serving: int8/fp8 paged KV storage + quantized TP all-reduce.

Two independent levers, one module (ISSUE 15):

* **KV-pool quantization** — K/V projections are quantized at
  page-write time with one fp32 scale per (kv_head, page, slot), stored
  in a *scale pool* that parallels the data pools.  One logical page is
  a data slab plus a scale slab: the allocator, page tables, prefix
  cache and overflow routing never see the difference (accounting is
  page-count based, so it stays byte-identical in bookkeeping terms).
  Dequantization happens inside the attention paths — jnp reference and
  Pallas kernels alike — so quantized pages ride the exact same unified
  ragged/decode/prefill executables.

* **Quantized all-reduce** — an EQuARX-style block-scaled int8
  all-reduce (:func:`quantized_psum`) for the row-parallel psum that
  dominates TP decode at small hidden sizes.  The local partial sum is
  blocked along the hidden axis, each block quantized against its own
  abs-max scale, and int8 payloads + scales are all-gathered; every
  shard dequantizes and reduces in the same fixed shard order, so the
  result stays *replicated* (bit-identical across shards) and the
  sampling invariant of the TP engine is preserved.

This module is imported lazily and ONLY when a quantized mode is
requested (``kv_dtype="int8"|"fp8"`` or ``tp_quantized_allreduce=True``).
``kv_dtype="fp32"``/``"bf16"`` engines must never touch it — enforced by
a poisoned-sys.modules test, same pattern as the tp module.
"""
from dataclasses import dataclass
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp

__all__ = [
    "KVQuantSpec", "resolve_kv_dtype", "quantize_tokens", "dequantize",
    "quantized_psum", "kv_pool_bytes", "measure_roundtrip_error",
]

# scale pools are always fp32: one scale per (kv_head, page, slot),
# stored as a rank-4 (kvh, num_pages, page_size, 1) slab so it shards
# and scatters with the exact same index arithmetic as the data pools
SCALE_DTYPE = jnp.float32


@dataclass(frozen=True)
class KVQuantSpec:
    """Resolved description of a quantized KV storage format."""
    name: str              # "int8" | "fp8"
    storage_dtype: object  # jnp dtype for the data pools
    qmax: float            # largest representable magnitude post-scale

    @property
    def storage_itemsize(self) -> int:
        return jnp.dtype(self.storage_dtype).itemsize


def resolve_kv_dtype(kv_dtype: str, compute_dtype=None) -> KVQuantSpec:
    """Validate and resolve a quantized ``kv_dtype`` name.

    Raises a clear ``ValueError`` on unsupported combos instead of
    letting a bad dtype surface as a cryptic XLA error three layers
    down (satellite: the old code silently assumed fp32 pools).
    """
    if kv_dtype == "int8":
        spec = KVQuantSpec("int8", jnp.int8, 127.0)
    elif kv_dtype == "fp8":
        if not hasattr(jnp, "float8_e4m3fn"):
            raise ValueError(
                "kv_dtype='fp8' needs jnp.float8_e4m3fn, which this jax "
                "build does not provide; use kv_dtype='int8' instead")
        spec = KVQuantSpec("fp8", jnp.float8_e4m3fn, 448.0)
    else:
        raise ValueError(
            f"unsupported quantized kv_dtype {kv_dtype!r}: "
            "expected 'int8' or 'fp8'")
    if compute_dtype is not None:
        cd = jnp.dtype(compute_dtype)
        if cd not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
            raise ValueError(
                f"kv_dtype={kv_dtype!r} requires a float32/bfloat16 "
                f"compute dtype, got {cd.name}")
    return spec


def quantize_tokens(x: jnp.ndarray,
                    spec: KVQuantSpec) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Quantize fresh K/V projections along the head dimension.

    ``x`` is (..., head_dim); returns ``(q, scale)`` with ``q`` of
    ``spec.storage_dtype`` and the same shape, and ``scale`` fp32 of
    shape (..., 1) — one scale per (token, kv_head), which becomes the
    per-slot scale once scattered into the scale pool.  Rounding is
    deterministic (round-half-to-even via jnp.round): parity across
    horizon/chunking/prefix legs depends on every path writing the
    exact same quantized bytes for the same token.
    """
    amax = jnp.max(jnp.abs(x.astype(SCALE_DTYPE)), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / spec.qmax, 1.0)
    q = jnp.clip(x.astype(SCALE_DTYPE) / scale, -spec.qmax, spec.qmax)
    if jnp.dtype(spec.storage_dtype) == jnp.dtype(jnp.int8):
        q = jnp.round(q)
    return q.astype(spec.storage_dtype), scale


def dequantize(q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`quantize_tokens`: ``q`` (..., head_dim) of the
    storage dtype, ``scale`` fp32 broadcastable against it."""
    return q.astype(SCALE_DTYPE) * scale


def block_quantize(x: jnp.ndarray,
                   block: int = 256) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The shard-local half of :func:`quantized_psum`: ``x`` (..., hidden)
    padded to a multiple of ``block``, split into block-wide chunks along
    the hidden axis, each quantized against its own abs-max.  Returns
    ``(q, scale)`` with ``q`` int8 of shape (..., nblocks, block) and
    ``scale`` fp32 of shape (..., nblocks, 1).  Factored out so the
    ring-overlapped all-reduce (serving/overlap.py) moves byte-identical
    payloads to the all_gather form — rows are quantized independently,
    so quantizing a micro-row chunk equals slicing the full quantization.
    """
    h = x.shape[-1]
    nblocks = -(-h // block)
    pad = nblocks * block - h
    xp = x.astype(jnp.float32)
    if pad:
        xp = jnp.pad(xp, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    xb = xp.reshape(x.shape[:-1] + (nblocks, block))
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.round(jnp.clip(xb / scale, -127.0, 127.0)).astype(jnp.int8)
    return q, scale


def block_dequant_sum(qg: jnp.ndarray, sg: jnp.ndarray, h: int,
                      out_dtype) -> jnp.ndarray:
    """The replicated half of :func:`quantized_psum`: gathered int8
    payloads ``qg`` (tp, ..., nblocks, block) and scales ``sg``
    (tp, ..., nblocks, 1) dequantized and summed in fixed shard order
    (one ``jnp.sum`` over the leading shard axis), unpadded back to
    hidden size ``h``.  The ring-overlapped reduction feeds this the
    SAME expression on ring-collected buffers, so both transports
    produce bit-identical results."""
    full = jnp.sum(qg.astype(jnp.float32) * sg, axis=0)
    lead = full.shape[:-2]
    out = full.reshape(lead + (full.shape[-2] * full.shape[-1],))
    if out.shape[-1] != h:
        out = out[..., :h]
    return out.astype(out_dtype)


def quantized_psum(x: jnp.ndarray, axis_name: str,
                   block: int = 256) -> jnp.ndarray:
    """EQuARX-style block-scaled int8 all-reduce over a mesh axis.

    The shard-local partial sum ``x`` (..., hidden) is split into
    ``block``-wide chunks along the hidden axis, each quantized against
    its own abs-max; int8 payloads + fp32 scales are all-gathered and
    every shard dequantizes and sums in fixed shard order.  All shards
    therefore compute the identical fp32 result — the replicated-output
    invariant the TP engine's sampling path relies on.  Wire cost per
    element drops from 4 bytes to ~1 byte (+ scales, amortized 1/block).
    """
    q, scale = block_quantize(x, block)
    qg = jax.lax.all_gather(q, axis_name)          # (tp, ..., nb, block)
    sg = jax.lax.all_gather(scale, axis_name)      # (tp, ..., nb, 1)
    return block_dequant_sum(qg, sg, x.shape[-1], x.dtype)


def kv_pool_bytes(num_layers: int, num_pages: int, page_size: int,
                  num_kv_heads: int, head_dim: int,
                  *, itemsize: int, quantized: bool) -> int:
    """Total bytes for a K+V pool set (data slabs + scale slabs)."""
    slots = num_layers * num_pages * page_size * num_kv_heads
    data = 2 * slots * head_dim * itemsize
    scales = 2 * slots * jnp.dtype(SCALE_DTYPE).itemsize if quantized else 0
    return data + scales


def measure_roundtrip_error(spec: KVQuantSpec, head_dim: int,
                            samples: int = 512, seed: int = 0) -> float:
    """One-shot quantize→dequantize RMS relative error on gaussian data.

    Runs once at engine construction (cold path) to populate the
    ``serving_kv_quant_rms_error`` gauge — the hot path keeps no fp32
    originals, so quantization error can only be characterized offline.
    """
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(samples, head_dim).astype(np.float32))
    q, scale = quantize_tokens(x, spec)
    err = dequantize(q, scale) - x
    num = jnp.sqrt(jnp.mean(err * err))
    den = jnp.sqrt(jnp.mean(x * x)) + 1e-12
    # construction-time probe, never reached from the step hot path
    return float(np.asarray(num / den))  # noqa: HOST-SYNC — one-shot cold-path gauge fill at engine construction
