"""STALE-CAPTURE — identity guards via id() and jitted closures over self.

The PR 1 postmortem: the SOT guard compared ``id()`` of a captured object
against a stored integer; the object died, CPython reused the id, and the
guard judged a *different* object "unchanged" — stale bytecode ran with
fresh inputs. The fix (compare ``is`` against a held reference) only
works if nobody reintroduces the pattern, which is exactly what a linter
is for.

Three shapes fire:

  * ``id(x) == y`` / ``y != id(x)`` — an identity compared by value. An
    id is only meaningful while the object is alive AND you hold a
    reference; equality against a stored int guards nothing.
  * ``self.attr = id(x)`` — storing an identity for a later guard, the
    precursor of the same bug.
  * a jit-traced function (decorated or passed to ``jax.jit``/friends)
    whose body *reads* ``self.<attr>`` — the attribute's value is baked
    in at trace time; later mutation of ``self`` silently keeps serving
    the stale constant from the executable cache.

Identity *maps* (``d[id(p)]`` with the object kept alive elsewhere) are
deliberately not flagged — that idiom holds its references.

Suppress with ``# noqa: STALE-CAPTURE — <reason>``.
"""
import ast
from typing import Iterator, List, Tuple

from ..core import Finding, ParsedModule, Rule, traced_functions


def _is_id_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "id")


class StaleCaptureRule(Rule):
    name = "STALE-CAPTURE"
    description = ("id()-based identity guards and jit-traced closures "
                   "reading mutable self state (the PR 1 stale-guard "
                   "class)")

    def check(self, module: ParsedModule) -> Iterator[Finding]:
        hits: List[Tuple[int, str]] = []
        for node in module.nodes():
            if isinstance(node, ast.Compare):
                sides = [node.left] + list(node.comparators)
                if any(_is_id_call(s) for s in sides) and any(
                        isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
                    hits.append((node.lineno,
                                 "id() compared by value — ids are reused "
                                 "after the object dies (the PR 1 stale "
                                 "SOT guard); hold the object and compare "
                                 "with `is` instead"))
            elif isinstance(node, ast.Assign):
                if _is_id_call(node.value) and any(
                        isinstance(t, ast.Attribute) for t in node.targets):
                    hits.append((node.lineno,
                                 "storing id() on an attribute for a later "
                                 "identity guard — the id is meaningless "
                                 "once the object dies; store the object "
                                 "(or a weakref) instead"))

        for info in traced_functions(module):
            fn = info.node
            body = fn.body
            if isinstance(body, list):
                body_nodes = [n for stmt in body for n in ast.walk(stmt)]
            else:  # Lambda: .body is a single expression, not a list
                body_nodes = list(ast.walk(body))
            for n in body_nodes:
                if (isinstance(n, ast.Attribute)
                        and isinstance(n.value, ast.Name)
                        and n.value.id == "self"
                        and isinstance(n.ctx, ast.Load)):
                    via = (f"@{info.traced_via}" if info.traced_via ==
                           "decorator" else info.traced_via)
                    hits.append((n.lineno,
                                 f"traced function `{info.name}` ({via}) "
                                 f"reads `self.{n.attr}` — captured at "
                                 f"trace time, so later mutation of self "
                                 f"silently serves a stale executable; "
                                 f"pass it as an argument (donated/static) "
                                 f"or snapshot it into a local before "
                                 f"tracing"))
                    break  # one finding per traced function is enough
        yield from self.findings(module, hits)
