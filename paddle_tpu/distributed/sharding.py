"""paddle.distributed.sharding — group_sharded_parallel entry point.

Ref: python/paddle/distributed/sharding/group_sharded.py (upstream layout,
unverified — mount empty).

The implementation lives in `paddle_tpu.parallel.zero` (ISSUE 16): one
engine behind both the paddle-compat surface here and the native
`paddle_tpu.parallel.zero_train_step` builder, on the unified mesh
substrate serving also uses.
"""
from ..parallel.zero import (  # noqa: F401
    GroupShardedOptimizerStage2, GroupShardedStage2, GroupShardedStage3,
    group_sharded_parallel, save_group_sharded_model,
)

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]
