"""The user-facing Tensor: a paddle-shaped mutable handle over a jax.Array.

Paddle's Tensor mutates in place and carries autograd state (ref:
paddle/fluid/pybind/eager_method.cc, upstream layout, unverified — mount
empty). jax arrays are immutable, so mutation is modeled as rebinding
`_data` (and, for differentiable in-place ops, rebinding the grad-node edge so
later reads see the new value in the autograd graph).

The wrapper is deliberately thin: every op goes through core.dispatch.apply_op
so eager/tape/AMP/static-capture all share one path, and jitted step functions
bypass the wrapper entirely by tracing the same registered pure functions.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from . import tape as tape_mod
from .dispatch import apply_op, apply_callable
from .dtype import convert_dtype, get_default_dtype
from .place import Place, _get_current_place
from ..ops.registry import get_op


def _unwrap_index(item):
    """Convert Tensors inside an index expression to raw arrays."""
    if isinstance(item, Tensor):
        return item._data
    if isinstance(item, tuple):
        return tuple(_unwrap_index(i) for i in item)
    if isinstance(item, list):
        return [_unwrap_index(i) for i in item]
    return item


class Tensor:
    __slots__ = ("_data", "stop_gradient", "grad", "_grad_node", "_out_index",
                 "name", "persistable", "_hooks", "process_mesh",
                 "placements", "__weakref__")

    def __init__(self, data, dtype=None, stop_gradient: bool = True,
                 name: str = ""):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, jax.Array):
            dt = convert_dtype(dtype)
            if dt is None and isinstance(data, (float,)):
                dt = get_default_dtype()
            if dt is None and isinstance(data, np.ndarray) and \
                    data.dtype == np.float64:
                dt = get_default_dtype()
            data = jnp.asarray(data, dtype=dt)
        elif dtype is not None:
            data = data.astype(convert_dtype(dtype))
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self._grad_node = None
        self._out_index = 0
        self.name = name
        self.persistable = False
        self._hooks = None
        self.process_mesh = None   # set by dist.shard_tensor/reshard
        self.placements = None

    # ------------------------------------------------------------ properties
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    def dim(self):
        return self._data.ndim

    def rank(self):
        return self._data.ndim

    @property
    def dtype(self):
        return np.dtype(self._data.dtype)

    @property
    def size(self) -> int:
        return int(self._data.size)

    def numel(self) -> int:
        return int(self._data.size)

    def element_size(self) -> int:
        return self.dtype.itemsize

    @property
    def place(self) -> Place:
        return _get_current_place()

    @property
    def is_leaf(self) -> bool:
        return self._grad_node is None

    @property
    def T(self):
        return apply_op(get_op("transpose"), self,
                        perm=list(range(self.ndim))[::-1])

    @property
    def mT(self):
        return apply_op(get_op("t"), self)

    # ------------------------------------------------------------ conversion
    def numpy(self) -> np.ndarray:
        return np.asarray(self._data)

    def item(self):
        return self._data.item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def astype(self, dtype):
        return apply_op(get_op("cast"), self, dtype=dtype)

    def cast(self, dtype):
        return self.astype(dtype)

    def to(self, *args, **kwargs):
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in ("cpu", "tpu", "gpu") or \
                    isinstance(a, Place):
                from .place import set_device  # resolve kind

                place = a if isinstance(a, Place) else None
                if place is None:
                    from .place import CPUPlace, TPUPlace

                    place = CPUPlace(0) if a == "cpu" else TPUPlace(0)
                out = Tensor(jax.device_put(out._data, place.jax_device()),
                             stop_gradient=out.stop_gradient)
            else:
                out = out.astype(a)
        return out

    def cpu(self):
        return self.to("cpu")

    def cuda(self, device_id=0):
        return self.to("tpu")

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    # -------------------------------------------------------------- autograd
    def backward(self, grad_tensor=None, retain_graph: bool = False):
        tape_mod.backward([self], None if grad_tensor is None else [grad_tensor],
                          retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def zero_grad(self):
        self.grad = None

    def detach(self) -> "Tensor":
        t = Tensor(self._data, stop_gradient=True)
        t.name = self.name
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self) -> "Tensor":
        return apply_op(get_op("clone"), self)

    def register_hook(self, hook):
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)

        class _Removable:
            def __init__(s, hooks, h):
                s._hooks, s._h = hooks, h

            def remove(s):
                if s._h in s._hooks:
                    s._hooks.remove(s._h)

        return _Removable(self._hooks, hook)

    def _accumulate_grad(self, g_data):
        g_data = g_data.astype(self._data.dtype) if \
            g_data.dtype != self._data.dtype else g_data
        if self._hooks:
            gt = Tensor(g_data, stop_gradient=True)
            for h in self._hooks:
                r = h(gt)
                if r is not None:
                    gt = r if isinstance(r, Tensor) else Tensor(r)
            g_data = gt._data
        if self.grad is None:
            self.grad = Tensor(g_data, stop_gradient=True)
        else:
            self.grad._data = self.grad._data + g_data

    def _snapshot(self) -> "Tensor":
        """Alias preserving the current value + autograd edge — recorded as
        the *input* of an in-place op so the pre-mutation graph stays
        reachable (jax arrays are immutable, so the data is safe to share)."""
        t = Tensor(self._data, stop_gradient=self.stop_gradient)
        t._grad_node = self._grad_node
        t._out_index = self._out_index
        t.name = self.name
        return t

    def _inplace_from(self, out: "Tensor"):
        """Adopt `out`'s value and autograd edge (in-place op semantics)."""
        self._data = out._data
        self._grad_node = out._grad_node
        self._out_index = out._out_index
        self.stop_gradient = out.stop_gradient
        return self

    # ------------------------------------------------------------- indexing
    def __getitem__(self, item):
        raw = _unwrap_index(item)

        def fn(x):
            return x[raw]

        return apply_callable("getitem", fn, self)

    def __setitem__(self, item, value):
        raw = _unwrap_index(item)
        snap = self._snapshot()
        if isinstance(value, Tensor):
            def fn(x, v):
                return x.at[raw].set(v.astype(x.dtype))

            out = apply_callable("setitem", fn, snap, value)
        else:
            val = jnp.asarray(value)

            def fn(x):
                return x.at[raw].set(val.astype(x.dtype))

            out = apply_callable("setitem", fn, snap)
        self._inplace_from(out)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # --------------------------------------------------------------- dunders
    def _binary(self, opname, other, reverse=False):
        if isinstance(other, np.ndarray):
            other = Tensor(other)
        a, b = (other, self) if reverse else (self, other)
        return apply_op(get_op(opname), a, b)

    def __add__(self, o):
        return self._binary("add", o)

    def __radd__(self, o):
        return self._binary("add", o, True)

    def __sub__(self, o):
        return self._binary("subtract", o)

    def __rsub__(self, o):
        return self._binary("subtract", o, True)

    def __mul__(self, o):
        return self._binary("multiply", o)

    def __rmul__(self, o):
        return self._binary("multiply", o, True)

    def __truediv__(self, o):
        return self._binary("divide", o)

    def __rtruediv__(self, o):
        return self._binary("divide", o, True)

    def __floordiv__(self, o):
        return self._binary("floor_divide", o)

    def __rfloordiv__(self, o):
        return self._binary("floor_divide", o, True)

    def __mod__(self, o):
        return self._binary("mod", o)

    def __rmod__(self, o):
        return self._binary("mod", o, True)

    def __pow__(self, o):
        if isinstance(o, (int, float)):
            return apply_op(get_op("pow_scalar"), self, value=o)
        return self._binary("elementwise_pow", o)

    def __rpow__(self, o):
        if isinstance(o, (int, float)):
            return apply_op(get_op("rpow_scalar"), self, value=o)
        return self._binary("elementwise_pow", o, True)

    def __matmul__(self, o):
        return self._binary("matmul", o)

    def __rmatmul__(self, o):
        return self._binary("matmul", o, True)

    def __neg__(self):
        return apply_op(get_op("neg"), self)

    def __abs__(self):
        return apply_op(get_op("abs"), self)

    def __invert__(self):
        op = "logical_not" if self.dtype == np.bool_ else "bitwise_not"
        return apply_op(get_op(op), self)

    def __and__(self, o):
        op = "logical_and" if self.dtype == np.bool_ else "bitwise_and"
        return self._binary(op, o)

    def __or__(self, o):
        op = "logical_or" if self.dtype == np.bool_ else "bitwise_or"
        return self._binary(op, o)

    def __xor__(self, o):
        op = "logical_xor" if self.dtype == np.bool_ else "bitwise_xor"
        return self._binary(op, o)

    def __eq__(self, o):
        return self._binary("equal", o)

    def __ne__(self, o):
        return self._binary("not_equal", o)

    def __lt__(self, o):
        return self._binary("less_than", o)

    def __le__(self, o):
        return self._binary("less_equal", o)

    def __gt__(self, o):
        return self._binary("greater_than", o)

    def __ge__(self, o):
        return self._binary("greater_equal", o)

    __hash__ = object.__hash__

    def __float__(self):
        return float(self._data)

    def __int__(self):
        return int(self._data)

    def __bool__(self):
        return bool(self._data)

    def __index__(self):
        return int(self._data)

    def __repr__(self):
        grad_info = "" if self.stop_gradient else ", stop_gradient=False"
        from ..tensor import PRINT_OPTIONS

        with np.printoptions(**PRINT_OPTIONS):
            body = repr(np.asarray(self._data))
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}"
                f"{grad_info},\n       {body})")

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return repr(self)

    def __array__(self, dtype=None):
        arr = np.asarray(self._data)
        return arr.astype(dtype) if dtype is not None else arr

    # jax interop: Tensors can be passed straight into jnp functions.
    def __jax_array__(self):
        return self._data

    # ------------------------------------------------- inplace paddle methods
    def _inplace_op(self, opname, *args, **kwargs):
        out = apply_op(get_op(opname), self._snapshot(), *args, **kwargs)
        return self._inplace_from(out)

    def add_(self, y):
        if isinstance(y, np.ndarray):
            y = Tensor(y)
        return self._inplace_op("add", y)

    def subtract_(self, y):
        return self._inplace_op("subtract", y)

    def multiply_(self, y):
        return self._inplace_op("multiply", y)

    def scale_(self, scale=1.0, bias=0.0, bias_after_scale=True):
        return self._inplace_op("scale", scale=scale, bias=bias,
                                bias_after_scale=bias_after_scale)

    def clip_(self, min=None, max=None):
        return self._inplace_op("clip", min=min, max=max)

    def tril_(self, diagonal=0):
        return self._inplace_op("tril", diagonal=diagonal)

    def triu_(self, diagonal=0):
        return self._inplace_op("triu", diagonal=diagonal)

    def remainder_(self, y):
        return self._inplace_op("remainder", y)

    def floor_(self):
        return self._inplace_op("floor")

    def ceil_(self):
        return self._inplace_op("ceil")

    def apply_(self, func):
        """In-place elementwise apply of a python callable on the HOST
        (paddle.Tensor.apply_ contract: func maps ndarray -> ndarray).
        Like upstream, refuses on grad-requiring tensors — the host
        callable is invisible to autograd."""
        if not self.stop_gradient:
            raise RuntimeError(
                "apply_ cannot be used on a tensor that requires grad "
                "(the host callable is outside the autograd graph)")
        self._data = jnp.asarray(np.asarray(func(np.asarray(self._data))),
                                 dtype=self._data.dtype)
        return self

    def apply(self, func):
        return Tensor(jnp.asarray(
            np.asarray(func(np.asarray(self._data))),
            dtype=self._data.dtype))

    @property
    def nbytes(self) -> int:
        return int(self._data.size) * self._data.dtype.itemsize

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    def fill_(self, value):
        self._data = jnp.full_like(self._data, value)
        return self

    def exponential_(self, lam=1.0):
        from .rng import next_key

        u = jax.random.uniform(next_key(), self._data.shape,
                               dtype=self._data.dtype)
        self._data = -jnp.log1p(-u) / lam
        return self

    def uniform_(self, min=-1.0, max=1.0, seed=0):
        from .rng import next_key

        self._data = jax.random.uniform(
            next_key(), self._data.shape, dtype=self._data.dtype,
            minval=min, maxval=max)
        return self

    def normal_(self, mean=0.0, std=1.0):
        from .rng import next_key

        self._data = mean + std * jax.random.normal(
            next_key(), self._data.shape, dtype=self._data.dtype)
        return self

    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        self._data = jnp.asarray(value, dtype=self._data.dtype).reshape(
            self._data.shape)
        return self

    def copy_(self, other, non_blocking=False):
        return self.set_value(other)

    def reconstruct_from_(self, other):
        self._data = other._data
        return self

    # value_and-shape helpers used across the framework
    def _replace_data(self, data):
        self._data = data
        return self


class Parameter(Tensor):
    """Trainable tensor (paddle.base.framework.Parameter analog)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer",
                 "need_clip", "is_distributed", "_sharding_axes",
                 "dist_spec", "sequence_parallel", "_asp_mask")

    def __init__(self, data, dtype=None, name: str = "", trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable,
                         name=name)
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False
        self._sharding_axes = None  # PartitionSpec-like hint for pjit paths
        self.dist_spec = None       # TP partition marks (mp_layers._mark)
        self.sequence_parallel = False

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


def _make_method(opname):
    op = get_op(opname)

    def method(self, *args, **kwargs):
        return apply_op(op, self, *args, **kwargs)

    method.__name__ = opname
    return method


# Tensor methods generated from the registry: method name -> op name.
_METHOD_TABLE = {
    # math
    "add": "add", "subtract": "subtract", "multiply": "multiply",
    "divide": "divide", "floor_divide": "floor_divide", "mod": "mod",
    "floor_mod": "mod",
    "remainder": "remainder", "pow": "elementwise_pow", "maximum": "maximum",
    "minimum": "minimum", "fmax": "fmax", "fmin": "fmin", "atan2": "atan2",
    "scale": "scale", "neg": "neg", "abs": "abs", "sqrt": "sqrt",
    "rsqrt": "rsqrt", "exp": "exp", "expm1": "expm1", "log": "log",
    "log2": "log2", "log10": "log10", "log1p": "log1p", "sin": "sin",
    "cos": "cos", "tan": "tan", "asin": "asin", "acos": "acos",
    "atan": "atan", "sinh": "sinh", "cosh": "cosh", "tanh": "tanh",
    "asinh": "asinh", "acosh": "acosh", "atanh": "atanh",
    "sigmoid": "sigmoid", "erf": "erf", "erfinv": "erfinv", "floor": "floor",
    "ceil": "ceil", "round": "round", "trunc": "trunc", "frac": "frac",
    "sign": "sign", "reciprocal": "reciprocal", "square": "square",
    "clip": "clip", "lerp": "lerp", "logit": "logit",
    "nan_to_num": "nan_to_num", "conj": "conj", "angle": "angle",
    "real": "real", "imag": "imag", "digamma": "digamma", "lgamma": "lgamma",
    "i0": "i0", "sinc": "sinc", "deg2rad": "deg2rad", "rad2deg": "rad2deg",
    "heaviside": "heaviside", "hypot": "hypot", "copysign": "copysign",
    "logaddexp": "logaddexp", "stanh": "stanh",
    # reduction
    "sum": "sum", "mean": "mean", "max": "max", "min": "min", "amax": "amax",
    "amin": "amin", "prod": "prod", "all": "all", "any": "any",
    "argmax": "argmax", "argmin": "argmin", "logsumexp": "logsumexp",
    "std": "std", "var": "var", "median": "median", "nanmean": "nanmean",
    "nansum": "nansum", "count_nonzero": "count_nonzero", "cumsum": "cumsum",
    "cumprod": "cumprod", "logcumsumexp": "logcumsumexp",
    # comparison / logical
    "equal": "equal", "not_equal": "not_equal", "less_than": "less_than",
    "less_equal": "less_equal", "greater_than": "greater_than",
    "greater_equal": "greater_equal", "equal_all": "equal_all",
    "isclose": "isclose", "allclose": "allclose", "isnan": "isnan",
    "isinf": "isinf", "isfinite": "isfinite",
    "logical_and": "logical_and", "logical_or": "logical_or",
    "logical_xor": "logical_xor", "logical_not": "logical_not",
    "bitwise_and": "bitwise_and", "bitwise_or": "bitwise_or",
    "bitwise_xor": "bitwise_xor", "bitwise_not": "bitwise_not",
    # manipulation
    "reshape": "reshape", "transpose": "transpose", "flatten": "flatten",
    "squeeze": "squeeze", "unsqueeze": "unsqueeze", "split": "split",
    "unbind": "unbind", "expand": "expand", "broadcast_to": "broadcast_to",
    "expand_as": "expand_as", "tile": "tile", "gather": "gather",
    "gather_nd": "gather_nd", "index_select": "index_select",
    "index_sample": "index_sample", "take_along_axis": "take_along_axis",
    "put_along_axis": "put_along_axis", "scatter": "scatter",
    "scatter_nd_add": "scatter_nd_add", "where": "where", "flip": "flip",
    "roll": "roll", "sort": "sort", "argsort": "argsort", "pad": "pad",
    "repeat_interleave": "repeat_interleave", "tril": "tril", "triu": "triu",
    "diag": "diag", "diagonal": "diagonal", "diag_embed": "diag_embed",
    "kron": "kron", "moveaxis": "moveaxis", "swapaxes": "swapaxes",
    "rot90": "rot90", "masked_fill": "masked_fill", "bincount": "bincount",
    "as_strided": "as_strided",
    # linalg
    "matmul": "matmul", "bmm": "bmm", "mm": "mm", "dot": "dot",
    "outer": "outer", "inner": "inner", "cross": "cross", "t": "t",
    "norm": "norm", "cholesky": "cholesky", "inverse": "inverse",
    "trace": "trace_op", "mv": "mv", "histogram": "histogram",
    # nn
    "relu": "relu", "softmax": "softmax", "log_softmax": "log_softmax",
    "one_hot": "one_hot",
}

for _m, _op in _METHOD_TABLE.items():
    if not hasattr(Tensor, _m):
        setattr(Tensor, _m, _make_method(_op))

# ops.yaml-generated methods attach through the same mechanism (the ops
# package — including yaml_ops — is fully registered before this module's
# body runs; see paddle_tpu/__init__ import order)
from ..ops.yaml_ops import METHOD_SPECS as _YAML_METHODS  # noqa: E402

for _m, _op in _YAML_METHODS.items():
    if not hasattr(Tensor, _m):
        setattr(Tensor, _m, _make_method(_op))


def _topk_method(self, k, axis=-1, largest=True, sorted=True):
    idx = apply_op(get_op("topk_indices"), self, k=k, axis=axis,
                   largest=largest)
    vals = apply_op(get_op("take_along_axis"), self, idx, axis=axis)
    return vals, idx


Tensor.topk = _topk_method


def _chunk_method(self, chunks, axis=0):
    return apply_op(get_op("split"), self, num_or_sections=chunks, axis=axis)


Tensor.chunk = _chunk_method


def _unique_method(self, return_index=False, return_inverse=False,
                   return_counts=False, axis=None):
    """Eager-only (dynamic output shape)."""
    arr = np.asarray(self._data)
    res = np.unique(arr, return_index=return_index,
                    return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if isinstance(res, tuple):
        out = [Tensor(r) for r in res]
        # paddle order: (out, index, inverse, counts)
        return tuple(out)
    return Tensor(res)


Tensor.unique = _unique_method


def _nonzero_method(self, as_tuple=False):
    """Eager-only (dynamic output shape)."""
    arr = np.asarray(self._data)
    nz = np.nonzero(arr)
    if as_tuple:
        return tuple(Tensor(n) for n in nz)
    return Tensor(np.stack(nz, axis=-1).astype(np.int64))


Tensor.nonzero = _nonzero_method


def _masked_select_method(self, mask):
    arr = np.asarray(self._data)
    m = np.asarray(mask._data if isinstance(mask, Tensor) else mask)
    return Tensor(arr[m])


Tensor.masked_select = _masked_select_method
