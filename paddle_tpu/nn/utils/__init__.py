"""nn.utils — weight_norm/spectral_norm/parameter vector helpers (ref:
python/paddle/nn/utils/*.py, upstream layout, unverified — mount empty).

Both reparametrizations are implemented as forward-pre-hooks: the effective
`weight` is recomputed from the registered parameters/buffers on every
forward, inside whatever trace (eager tape, jit, pjit) the forward runs
under — so gradients flow to the reparametrized parameters and the math
compiles into the same XLA program as the layer itself.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.tensor import Parameter, Tensor


def _like_param(src: Parameter, data) -> Parameter:
    """New Parameter carrying `src`'s training attrs (trainable flag,
    per-param LR, regularizer, clip) — the optimizer reads all four."""
    p = Parameter(data, trainable=src.trainable)
    p.optimize_attr = dict(src.optimize_attr)
    p.regularizer = src.regularizer
    p.need_clip = src.need_clip
    return p


def _set_effective(lay, name: str, eff: Tensor):
    """Install the recomputed weight; remember the last concrete value so a
    traced call (jit/to_static — eff's data is a tracer there) can be undone
    by the paired post-hook instead of leaking an escaped tracer into the
    layer's attribute."""
    if not isinstance(eff._data, jax.core.Tracer):
        lay.__dict__[f"_{name}_reparam_concrete"] = eff
    object.__setattr__(lay, name, eff)


def _make_restore_hook(name: str):
    def _restore(lay, _inputs, _outputs):
        cur = lay.__dict__.get(name)
        saved = lay.__dict__.get(f"_{name}_reparam_concrete")
        if (cur is not None and saved is not None
                and isinstance(cur._data, jax.core.Tracer)):
            object.__setattr__(lay, name, saved)
    return _restore


def _norm_axes(ndim: int, dim):
    if dim is None:
        return tuple(range(ndim))
    if dim < 0:
        dim += ndim
    return tuple(i for i in range(ndim) if i != dim)


def _row_norm(v, dim):
    """||v|| over every axis except `dim` (kept), differentiable."""
    axes = _norm_axes(len(v.shape), dim)
    sq = (v * v).sum(axis=list(axes), keepdim=True)
    return sq.sqrt()


def weight_norm(layer, name: str = "weight", dim: int = 0):
    """Reparametrize ``layer.<name>`` as ``g * v / ||v||``.

    Registers trainable ``<name>_g`` (per-`dim` magnitudes; scalar when
    ``dim is None``) and ``<name>_v`` (direction), removes the original
    parameter, and recomputes the effective weight at every forward.
    """
    if hasattr(layer, f"{name}_g"):
        raise ValueError(f"weight_norm already applied to {name!r}")
    w = getattr(layer, name)
    if not isinstance(w, Parameter):
        raise ValueError(f"{name!r} is not a Parameter of {type(layer)}")
    g0 = _row_norm(w, dim)
    layer.add_parameter(f"{name}_g", _like_param(w, g0._data))
    layer.add_parameter(f"{name}_v", _like_param(w, w._data))
    del layer._parameters[name]

    def _recompute(lay, _inputs=None):
        g = getattr(lay, f"{name}_g")
        v = getattr(lay, f"{name}_v")
        eff = v * (g / _row_norm(v, dim))
        _set_effective(lay, name, eff)

    helper = layer.register_forward_pre_hook(_recompute)
    post = layer.register_forward_post_hook(_make_restore_hook(name))
    _recompute(layer)
    # stash for remove_weight_norm
    layer.__dict__.setdefault("_weight_norm_hooks", {})[name] = \
        (helper, post, dim)
    return layer


def remove_weight_norm(layer, name: str = "weight"):
    """Fold g·v/||v|| back into a plain parameter and drop the hook."""
    hooks = layer.__dict__.get("_weight_norm_hooks", {})
    if name not in hooks:
        raise ValueError(f"weight_norm was not applied to {name!r}")
    helper, post, dim = hooks.pop(name)
    helper.remove()
    post.remove()
    layer.__dict__.pop(f"_{name}_reparam_concrete", None)
    g = getattr(layer, f"{name}_g")
    v = getattr(layer, f"{name}_v")
    eff = v * (g / _row_norm(v, dim))
    del layer._parameters[f"{name}_g"]
    layer.__dict__.pop(name, None)
    layer.add_parameter(name, _like_param(v, eff._data))
    del layer._parameters[f"{name}_v"]
    return layer


def spectral_norm(layer, name: str = "weight", n_power_iterations: int = 1,
                  eps: float = 1e-12, dim: int = 0):
    """Divide ``layer.<name>`` by its largest singular value.

    σ is estimated by power iteration on the matricized weight
    (``dim`` rows × everything-else columns). The ``u``/``v`` vectors are
    non-trainable buffers refreshed on each *training* forward (the paddle
    semantic); σ itself is computed differentiably as uᵀ W v so gradients
    see the normalization.
    """
    if hasattr(layer, f"{name}_orig"):
        raise ValueError(f"spectral_norm already applied to {name!r}")
    w = getattr(layer, name)
    if not isinstance(w, Parameter):
        raise ValueError(f"{name!r} is not a Parameter of {type(layer)}")
    ndim = len(w.shape)
    if dim < 0:
        dim += ndim
    h = w.shape[dim]
    cols = int(np.prod([w.shape[i] for i in range(ndim) if i != dim])) \
        if ndim > 1 else 1

    rng = np.random.default_rng(0)
    u0 = rng.standard_normal(h).astype(np.float32)
    v0 = rng.standard_normal(cols).astype(np.float32)
    layer.register_buffer(f"{name}_u", Tensor(u0 / np.linalg.norm(u0)))
    layer.register_buffer(f"{name}_v", Tensor(v0 / np.linalg.norm(v0)))
    layer.add_parameter(f"{name}_orig", _like_param(w, w._data))
    del layer._parameters[name]
    perm = [dim] + [i for i in range(ndim) if i != dim]

    def _recompute(lay, _inputs=None):
        w_p = getattr(lay, f"{name}_orig")
        mat = w_p.transpose(perm).reshape([h, cols]) if ndim > 1 else \
            w_p.reshape([h, 1])
        u = getattr(lay, f"{name}_u")
        v = getattr(lay, f"{name}_v")
        if getattr(lay, "training", True):
            # power iteration on values only — u/v are constants to autograd
            m = mat._data
            ud, vd = u._data, v._data
            for _ in range(n_power_iterations):
                vd = m.T @ ud
                vd = vd / (jnp.linalg.norm(vd) + eps)
                ud = m @ vd
                ud = ud / (jnp.linalg.norm(ud) + eps)
            u._data, v._data = ud, vd
        # lax.stop_gradient, not Tensor.detach: under jax-level autodiff
        # (hapi/static/jit paths) detach only flags the eager tape and the
        # power iteration would otherwise be differentiated through
        u_c = Tensor(jax.lax.stop_gradient(u._data))
        v_c = Tensor(jax.lax.stop_gradient(v._data))
        sigma = u_c.reshape([1, h]).matmul(mat).matmul(
            v_c.reshape([cols, 1])).reshape([1])
        eff = w_p / sigma
        _set_effective(lay, name, eff)

    helper = layer.register_forward_pre_hook(_recompute)
    post = layer.register_forward_post_hook(_make_restore_hook(name))
    _recompute(layer)
    layer.__dict__.setdefault("_spectral_norm_hooks", {})[name] = \
        (helper, post)
    return layer


def parameters_to_vector(parameters):
    datas = [p._data.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(datas))


def vector_to_parameters(vec, parameters):
    offset = 0
    for p in parameters:
        n = int(np.prod(p.shape)) if p.shape else 1
        p._data = vec._data[offset:offset + n].reshape(p._data.shape).astype(
            p._data.dtype)
        offset += n


# grad-clip utils live in nn/clip.py (float32-accumulated norms); re-export
from ..clip import clip_grad_norm_  # noqa: F401,E402


def clip_grad_value_(parameters, clip_value):
    """In-place elementwise gradient clip to [-clip_value, clip_value]."""
    params = [parameters] if not isinstance(parameters, (list, tuple)) \
        else list(parameters)
    cv = float(clip_value)
    for p in params:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -cv, cv)
