"""Tensor-parallel serving: Megatron-sharded executables + a partitioned
paged KV pool over a sub-mesh of local devices.

`TPContext` is the bridge between the serving engine's jitted step
families and a 1-axis `jax.sharding.Mesh` ("tp") of `tp_size` devices:

- **weight sharding** (Megatron-LM): QKV / gate / up projections are
  column-parallel (output dim sharded, each shard owns whole heads),
  O / down projections are row-parallel (input dim sharded, partial
  sums) — so each attention block and each MLP block costs exactly ONE
  `lax.psum` over the tp axis, issued inside the row-parallel Linear
  before its (replicated) bias. Embeddings, norms and the LM head stay
  replicated: the final logits are bit-identical on every shard, and
  fused sampling runs from the full distribution everywhere, keeping
  PRNG streams and emitted tokens identical to `tp_size=1`. GPT's fused
  `qkv = Linear(h, 3h)` weight is column-INTERLEAVED before placement
  (global layout (3, heads, hd) -> (tp, 3, heads/tp, hd)) so each
  shard's contiguous slice reshapes to its own (3, heads/tp, hd) block;

- **sharded paged KV pool**: the per-layer pools keep their
  (kv_heads, num_pages, page_size, head_dim) logical shape but are
  placed `P("tp", None, None, None)` — each shard owns a
  (kv_heads/tp, num_pages, page_size, head_dim) slab. Page tables, the
  null page, `BlockAllocator` accounting, prefix-cache page ids and
  scheduler admission stay shard-replicated and byte-identical to the
  single-device engine: one logical page = tp physical slabs, so no
  scheduler / recovery / cluster policy changes at all;

- **shard-local model**: the engine's model reshapes activations with
  its config's STATIC head counts, so the sharded executables trace a
  skeleton clone of the model whose attention modules count heads/tp
  (weights are rebound per call by `call_functional`, so the skeleton's
  own parameters are freed to 0-d stubs) and whose row-parallel Linears
  are retyped to `_RowParallelPsumLinear` — or, under
  `TPContext(overlap=True, overlap_chunks=K)`, to the ring-overlapped
  counterparts in serving/overlap.py, which split each all-reduce into
  K micro-row `lax.ppermute` ring chunks interleaved with the consumer
  matmuls while keeping tokens bit-identical (fixed shard-order
  accumulation, ISSUE 18);

- **execution**: `wrap_prefill_exec` / `wrap_decode_exec` wrap the
  engine's unchanged step bodies in `shard_map` over the tp axis —
  params/pools sharded per the specs above, everything else (ids, page
  tables, positions, PRNG key data, sampling knobs) replicated.

Mesh construction lives on the unified substrate
(`paddle_tpu.parallel.mesh`, shared with the ZeRO training engine):
devices are sorted by id, so any `jax.devices()` ordering produces the
same mesh — snapshot/restore and cluster sub-mesh carving stay
deterministic across processes. GQA validation requires
`kv_heads % tp == 0` (each shard owns whole KV-head groups).

Nothing in this module is imported unless `ServingEngine(tp_size>1)` —
the `tp_size=1` path runs zero TP code (pinned by tests).
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

try:                                   # newer jax exports it at top level
    from jax import shard_map as _shard_map  # type: ignore
except ImportError:                    # jax 0.4.x experimental home
    from jax.experimental.shard_map import shard_map as _shard_map

# the unified mesh substrate (ISSUE 16): device ordering and mesh
# construction are shared with the training engines in
# paddle_tpu.parallel — TP_AXIS here IS parallel.mesh.TP_AXIS
from ..core.tensor import Tensor
from ..parallel.mesh import TP_AXIS, build_mesh, device_order
from .. import nn

__all__ = ["TPContext", "validate_tp_config", "tp_device_order"]


def tp_device_order(devices=None):
    """Sorted-by-id device list — delegates to the substrate's
    `parallel.mesh.device_order`, THE canonical ordering for every mesh
    in the repo (engine sub-mesh, cluster carving, training grid), so
    snapshot/restore and cluster replica carving stay deterministic no
    matter how the caller's list was shuffled."""
    return device_order(devices)


def validate_tp_config(cfg, tp_size: int) -> None:
    """Divisibility contract for Megatron sharding of this config.
    `kv_heads % tp == 0` is the GQA rule: a KV head's pool slab lives on
    exactly one shard, and every query head of its group lives with it
    (heads % tp == 0 keeps the per-shard rep factor integral)."""
    heads = cfg.num_attention_heads
    kv = getattr(cfg, "num_key_value_heads", heads)
    inter = cfg.intermediate_size
    if tp_size < 2:
        raise ValueError(f"tp_size must be >= 2 for a TPContext "
                         f"(got {tp_size}); tp_size=1 is the plain engine")
    if heads % tp_size:
        raise ValueError(
            f"num_attention_heads ({heads}) must be divisible by "
            f"tp_size ({tp_size})")
    if kv % tp_size:
        raise ValueError(
            f"num_key_value_heads ({kv}) must be divisible by tp_size "
            f"({tp_size}) — each TP shard owns whole KV heads (GQA "
            "groups never straddle shards)")
    if inter % tp_size:
        raise ValueError(
            f"intermediate_size ({inter}) must be divisible by tp_size "
            f"({tp_size})")


class _RowParallelPsumLinear(nn.Linear):
    """Shard-local row-parallel Linear: the bound weight is the shard's
    (in/tp, out) slice, so the matmul yields a PARTIAL sum — one
    `lax.psum` over the tp axis completes it, and the (replicated) bias
    is added AFTER the reduction (a pre-psum bias would be counted tp
    times). Instances are retyped in place on the skeleton model
    (`linear.__class__ = _RowParallelPsumLinear`), so parameter names —
    what `call_functional` binds by — are untouched."""

    def forward(self, x):
        y = x.matmul(self.weight)
        y = Tensor(jax.lax.psum(y._data, TP_AXIS))
        if self.bias is not None:
            y = y + self.bias
        return y


class _RowParallelQuantPsumLinear(nn.Linear):
    """`_RowParallelPsumLinear` with the psum swapped for the EQuARX-style
    block-scaled int8 all-reduce (`quant.quantized_psum`): the partial sum
    travels as int8 blocks + fp32 scales instead of fp32, and every shard
    dequantizes/sums in fixed shard order — the result stays replicated,
    so sampling and PRNG streams remain shard-identical (just not
    bit-identical to the fp32 psum). Selected by
    `TPContext(quantized_allreduce=True)`; the quant import is deferred
    to trace time so an un-quantized TP engine never touches it."""

    def forward(self, x):
        from .quant import quantized_psum

        y = x.matmul(self.weight)
        y = Tensor(quantized_psum(y._data, TP_AXIS))
        if self.bias is not None:
            y = y + self.bias
        return y


# suffix -> PartitionSpec tables (matched against named_parameters keys);
# Linear weights are (in_features, out_features): column-parallel shards
# axis 1, row-parallel shards axis 0
_LLAMA_COL_W = (".q_proj.weight", ".k_proj.weight", ".v_proj.weight",
                ".gate_proj.weight", ".up_proj.weight")
_LLAMA_ROW_W = (".o_proj.weight", ".down_proj.weight")
_GPT_COL_W = (".attn.qkv.weight", ".ffn_in.weight")
_GPT_COL_B = (".attn.qkv.bias", ".ffn_in.bias")
_GPT_ROW_W = (".attn.out.weight", ".ffn_out.weight")
# GPT's fused qkv output dim is laid out (3, heads, hd); these params are
# interleaved to (tp, 3, heads/tp, hd) before contiguous column sharding
_GPT_QKV = (".attn.qkv.weight", ".attn.qkv.bias")


class TPContext:
    """Everything `ServingEngine(tp_size=N)` needs to run its executable
    families under `shard_map` over a tp sub-mesh: the mesh (sorted
    device ids), per-parameter PartitionSpecs, the KV pool spec, the
    shard-local skeleton model, and placement/wrapping helpers. Built
    once per engine; `jit_key` disambiguates the model-level jit cache
    per (tp degree, device subset), so cluster replicas on different
    sub-meshes never share a compiled executable."""

    def __init__(self, model, tp_size: int, devices=None,
                 quantized_allreduce: bool = False,
                 overlap: bool = False, overlap_chunks: int = 2):
        from ..models.generation import _config_of

        self.tp_size = int(tp_size)
        self.quantized_allreduce = bool(quantized_allreduce)
        # collective/compute overlap (ISSUE 18): split each row-parallel
        # all-reduce into `overlap_chunks` micro-row ring chunks
        # interleaved with the consumer matmuls. chunks=1 normalizes the
        # request OFF entirely — one chunk IS the serial schedule, so the
        # engine keeps the serial retype, the serial jit keys, and
        # literally reuses the serial executables (pinned by tests)
        self.overlap_chunks = int(overlap_chunks)
        if self.overlap_chunks < 1:
            raise ValueError(
                f"overlap_chunks must be >= 1, got {overlap_chunks}")
        self.overlap = bool(overlap) and self.overlap_chunks > 1
        self.cfg = _config_of(model)
        validate_tp_config(self.cfg, self.tp_size)
        if hasattr(model, "llama"):
            self.family = "llama"
        elif hasattr(model, "gpt"):
            self.family = "gpt"
        else:
            raise ValueError(
                "tensor-parallel serving defines Megatron sharding specs "
                "for the LLaMA/GPT decoder families; got "
                f"{type(model).__name__}")
        devs = tp_device_order(devices)
        if len(devs) < self.tp_size:
            raise ValueError(
                f"tp_size={self.tp_size} needs that many devices, got "
                f"{len(devs)}")
        self.devices: Tuple = tuple(devs[:self.tp_size])
        # byte-identical to the pre-substrate construction: the sorted
        # device prefix reshaped onto the one (tp,) axis
        self.mesh = build_mesh(((TP_AXIS, self.tp_size),), self.devices)
        self.num_layers = self.cfg.num_hidden_layers
        self.pool_spec = P(TP_AXIS, None, None, None)
        self.model = model
        self.param_specs = self._build_param_specs(model)
        self.shard_model = self._build_shard_model(model)
        # model-level jit-cache key suffix: tp degree + device identity
        # (+ a marker when the quantized all-reduce is traced in — the
        # executables differ, so the cache must never mix the two; + the
        # ring-overlap marker ONLY when overlap is effectively on, so
        # serial keys stay byte-identical to pre-overlap engines)
        self.jit_key = ("tp", self.tp_size,
                        tuple(d.id for d in self.devices)) \
            + (("qar",) if self.quantized_allreduce else ()) \
            + (("ovl", self.overlap_chunks) if self.overlap else ())
        self._probes: Dict[int, object] = {}
        # construction-time overlap probe (serial reduce+consume wall vs
        # the ring-overlapped pipeline, as a fraction of the collective
        # wall) — the documented number behind stats()["tp"]
        # ["overlap_fraction"]; None on serial engines (zero overlap
        # code runs, raise-on-touch pinned)
        self.overlap_fraction: Optional[float] = None
        if self.overlap:
            from .overlap import measure_overlap_fraction

            self.overlap_fraction = measure_overlap_fraction(
                self.mesh, self.tp_size, self.cfg.hidden_size,
                self.overlap_chunks, self.quantized_allreduce)

    # ------------------------------------------------------------ sharding
    def _spec_for(self, name: str) -> P:
        if self.family == "llama":
            if name.endswith(_LLAMA_COL_W):
                return P(None, TP_AXIS)
            if name.endswith(_LLAMA_ROW_W):
                return P(TP_AXIS, None)
        else:
            if name.endswith(_GPT_COL_W):
                return P(None, TP_AXIS)
            if name.endswith(_GPT_COL_B):
                return P(TP_AXIS)
            if name.endswith(_GPT_ROW_W):
                return P(TP_AXIS, None)
        # embeddings / norms / lm_head / row-parallel biases: replicated
        return P()

    def _build_param_specs(self, model) -> Dict[str, P]:
        from ..jit.functional import extract_state

        params, _ = extract_state(model)
        return {name: self._spec_for(name) for name in params}

    def _interleave_qkv(self, arr):
        """Reorder a fused-QKV param's output dim from (3, heads, hd) to
        (tp, 3, heads/tp, hd) so a CONTIGUOUS column shard is one
        shard's own [q|k|v] block — the shard-local
        `reshape(b, s, 3, heads/tp, hd)` then splits correctly."""
        nh = self.cfg.num_attention_heads
        hd = self.cfg.hidden_size // nh
        tp = self.tp_size
        lead = arr.shape[:-1]
        x = arr.reshape(lead + (3, tp, nh // tp, hd))
        x = jnp.moveaxis(x, -3, -4)            # (..., tp, 3, nh/tp, hd)
        return x.reshape(lead + (3 * nh * hd,))

    def shard_params(self, params: Dict[str, jnp.ndarray]
                     ) -> Dict[str, jnp.ndarray]:
        """Place the engine's full parameter dict onto the mesh per the
        Megatron specs (GPT fused-QKV params are column-interleaved
        first). Each shard materializes only its slice."""
        out = {}
        for name, arr in params.items():
            if self.family == "gpt" and name.endswith(_GPT_QKV):
                arr = self._interleave_qkv(arr)
            out[name] = jax.device_put(
                arr, NamedSharding(self.mesh, self.param_specs[name]))
        return out

    def replicate(self, tree):
        """Place a pytree fully replicated on the mesh (buffers)."""
        sh = NamedSharding(self.mesh, P())
        return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh),
                                      tree)

    # ------------------------------------------------------ skeleton model
    def _build_shard_model(self, model):
        """Shard-local clone of the model: same class + FULL config (so
        derived sizes like head_dim stay right), then attention head
        counts divided by tp (the static reshape constants) and the
        row-parallel Linears retyped to the psum variant. Its own
        freshly-initialized weights are immediately freed to 0-d stubs —
        `call_functional` rebinds every parameter per call, and the
        sharded executables bind the shard-local slices."""
        skel = type(model)(self.cfg)
        skel.eval()
        tp = self.tp_size
        row_cls = (_RowParallelQuantPsumLinear if self.quantized_allreduce
                   else _RowParallelPsumLinear)
        if self.family == "llama":
            for layer in skel.llama.layers:
                att = layer.self_attn
                att.num_heads //= tp
                att.num_kv_heads //= tp
                if not self.overlap:
                    att.o_proj.__class__ = row_cls
                    layer.mlp.down_proj.__class__ = row_cls
        else:
            for blk in skel.gpt.blocks:
                blk.attn.num_heads //= tp
                if not self.overlap:
                    blk.attn.out.__class__ = row_cls
                    blk.ffn_out.__class__ = row_cls
        if self.overlap:
            # ring-overlapped retype (ISSUE 18): row Linears become ring
            # partials and the decoder layers become the chunk-pipelined
            # drivers. The import stays inside the branch — serial TP
            # engines run ZERO overlap code (raise-on-touch pinned)
            from .overlap import install_overlap

            install_overlap(skel, self.family, tp, self.overlap_chunks,
                            self.quantized_allreduce)
        for _, p in skel.named_parameters():
            p._data = jnp.zeros((), p._data.dtype)
        return skel

    # ----------------------------------------------------------- wrapping
    def _pool_specs(self, pools=None):
        """Specs matching the engine's pool structure: 2-tuples (k, v)
        for plain pools, 4-tuples (k, v, k_scale, v_scale) for quantized
        ones. Every leaf — scale slabs included, they are rank-4 with
        the same leading kv-head axis — shards under the one pool spec;
        with no pools given (probe paths) assume the classic 2-tuples."""
        if pools is None:
            return [(self.pool_spec, self.pool_spec)] * self.num_layers
        return jax.tree_util.tree_map(lambda _: self.pool_spec, pools)

    @staticmethod
    def _repl_like(tree):
        return jax.tree_util.tree_map(lambda _: P(), tree)

    def wrap_prefill_exec(self, fn):
        """shard_map a prefill-family step
        `(params, buffers, ids, pools, *rest) -> (tok, key_data, pools)`
        over the tp axis: params per spec, pools kv-head-sharded,
        everything else replicated. The sampled token and key state are
        computed from the replicated logits on EVERY shard, so the
        `P()` outputs are genuinely identical across devices
        (check_rep=False: 0.4.x can't prove replication through the
        PRNG ops, but the final psum makes it so by construction)."""
        param_specs, mesh = self.param_specs, self.mesh

        def wrapped(params, buffers, ids, pools, *rest):
            pool_specs = self._pool_specs(pools)
            return _shard_map(
                fn, mesh=mesh,
                in_specs=(param_specs, self._repl_like(buffers), P(),
                          pool_specs) + tuple(P() for _ in rest),
                out_specs=(P(), P(), pool_specs),
                check_rep=False,  # noqa: COLLECTIVE-MESH — pool outputs are per-shard by design (kv-head-sharded pages); rep checking would reject the contract
                )(params, buffers, ids, pools, *rest)
        return wrapped

    def wrap_decode_exec(self, fn):
        """shard_map the fused decode+sample block
        `(params, buffers, tokens, pools, *rest) ->
        (emitted, pools, tokens, positions, key_data, remaining)` —
        same placement contract as `wrap_prefill_exec`."""
        param_specs, mesh = self.param_specs, self.mesh

        def wrapped(params, buffers, tokens, pools, *rest):
            pool_specs = self._pool_specs(pools)
            return _shard_map(
                fn, mesh=mesh,
                in_specs=(param_specs, self._repl_like(buffers), P(),
                          pool_specs) + tuple(P() for _ in rest),
                out_specs=(P(), pool_specs, P(), P(), P(), P()),
                check_rep=False,  # noqa: COLLECTIVE-MESH — pool outputs are per-shard by design (kv-head-sharded pages); rep checking would reject the contract
                )(params, buffers, tokens, pools, *rest)
        return wrapped

    def wrap_ragged_exec(self, fn):
        """shard_map the one-dispatch ragged mixed step
        `(params, buffers, flat_ids, pools, *rest) ->
        (emitted, pools, key_out)` — same placement contract as the
        other families: the flat token buffer, page tables, row ids and
        every per-row array are replicated, the KV pools kv-head-
        sharded, and the emitted block + key state are computed from
        replicated logits on every shard."""
        param_specs, mesh = self.param_specs, self.mesh

        def wrapped(params, buffers, flat_ids, pools, *rest):
            pool_specs = self._pool_specs(pools)
            return _shard_map(
                fn, mesh=mesh,
                in_specs=(param_specs, self._repl_like(buffers), P(),
                          pool_specs) + tuple(P() for _ in rest),
                out_specs=(P(), pool_specs, P()),
                check_rep=False,  # noqa: COLLECTIVE-MESH — pool outputs are per-shard by design (kv-head-sharded pages); rep checking would reject the contract
                )(params, buffers, flat_ids, pools, *rest)
        return wrapped

    def wrap_spec_exec(self, fn):
        """shard_map the speculative decode block
        `(params, buffers, tokens, pools, *rest) ->
        (emitted, pools, tokens, positions, key_data, remaining,
        spec_stats)` — the decode contract plus the per-row accept
        counters, which like the emitted block are computed from
        replicated logits on every shard."""
        param_specs, mesh = self.param_specs, self.mesh

        def wrapped(params, buffers, tokens, pools, *rest):
            pool_specs = self._pool_specs(pools)
            return _shard_map(
                fn, mesh=mesh,
                in_specs=(param_specs, self._repl_like(buffers), P(),
                          pool_specs) + tuple(P() for _ in rest),
                out_specs=(P(), pool_specs, P(), P(), P(), P(), P()),
                check_rep=False,  # noqa: COLLECTIVE-MESH — pool outputs are per-shard by design (kv-head-sharded pages); rep checking would reject the contract
                )(params, buffers, tokens, pools, *rest)
        return wrapped

    def wrap_spec_ragged_exec(self, fn):
        """shard_map the speculative ragged mixed step
        `(params, buffers, flat_ids, pools, *rest) ->
        (emitted, pools, key_out, spec_stats)` — the ragged contract
        plus the per-row accept counters."""
        param_specs, mesh = self.param_specs, self.mesh

        def wrapped(params, buffers, flat_ids, pools, *rest):
            pool_specs = self._pool_specs(pools)
            return _shard_map(
                fn, mesh=mesh,
                in_specs=(param_specs, self._repl_like(buffers), P(),
                          pool_specs) + tuple(P() for _ in rest),
                out_specs=(P(), pool_specs, P(), P()),
                check_rep=False,  # noqa: COLLECTIVE-MESH — pool outputs are per-shard by design (kv-head-sharded pages); rep checking would reject the contract
                )(params, buffers, flat_ids, pools, *rest)
        return wrapped

    # -------------------------------------------------------- observability
    @staticmethod
    def probe_best_of(trials: Sequence[float]) -> float:
        """Aggregate one probe sample from its timing trials: the
        minimum. The floor of repeated identical dispatches IS the
        collective + steady-state dispatch; everything above it is host
        scheduling noise. Monotone non-increasing as trials are added —
        pinned by the probe-monotonicity test."""
        return min(trials)

    def collective_seconds(self, samples: int = 3, rows: int = 1,
                           best_of: int = 3) -> List[float]:
        """Measured wall seconds per all-reduce on THIS sub-mesh: a
        jitted psum of a replicated (rows, hidden) f32 buffer — the
        payload shape of one decode-step residual all-reduce (the model
        issues 2*num_layers of these per decode step). Feeds the
        `serving_tp_collective_seconds` histogram and the bench phase's
        collective-time breakdown, and is the serial baseline the
        overlap probe compares against. Includes one dispatch's host
        overhead — on CPU meshes that dominates, which is exactly the
        honest number.

        Each sample is best-of-`best_of` timed calls after TWO warm-up
        dispatches (bugfix: the first post-compile call still pays
        dispatch-queue setup; timing it reported queueing, not the
        collective)."""
        fn = self._probes.get(rows)
        if fn is None:
            mesh = self.mesh
            if self.quantized_allreduce:
                from .quant import quantized_psum

                def reduce_one(y):
                    return quantized_psum(y, TP_AXIS)
            else:
                def reduce_one(y):
                    return jax.lax.psum(y, TP_AXIS)

            def allreduce(x):
                return _shard_map(reduce_one,
                                  mesh=mesh, in_specs=P(), out_specs=P(),
                                  check_rep=False,  # noqa: COLLECTIVE-MESH — probe psum of a replicated buffer; rep tracking adds latency to the very overhead being measured
                                  )(x)
            fn = jax.jit(allreduce)
            self._probes[rows] = fn
        x = jax.device_put(
            jnp.zeros((rows, self.cfg.hidden_size), jnp.float32),
            NamedSharding(self.mesh, P()))
        fn(x).block_until_ready()              # compile + first dispatch
        fn(x).block_until_ready()              # warm-up: steady-state queue
        out = []
        for _ in range(max(int(samples), 1)):
            trials = []
            for _ in range(max(int(best_of), 1)):
                t0 = time.perf_counter()
                fn(x).block_until_ready()
                trials.append(time.perf_counter() - t0)
            out.append(self.probe_best_of(trials))
        return out

    def describe(self) -> Dict[str, object]:
        """Shape of the TP deployment for stats()/debugging: what is
        per-shard vs replicated."""
        cfg = self.cfg
        kv = getattr(cfg, "num_key_value_heads", cfg.num_attention_heads)
        return {
            "tp_size": self.tp_size,
            "quantized_allreduce": self.quantized_allreduce,
            "overlap": self.overlap,
            "overlap_chunks": self.overlap_chunks if self.overlap else 1,
            "overlap_fraction": self.overlap_fraction,
            "devices": [d.id for d in self.devices],
            "kv_heads_per_shard": kv // self.tp_size,
            "heads_per_shard": cfg.num_attention_heads // self.tp_size,
            "replicated": ["page_tables", "allocator", "scheduler",
                           "sampling", "logits", "key_state"],
        }
