"""Linear-algebra ops. Matmuls are MXU-bound on TPU — everything here keeps
them batched and lets XLA pick tiling; precision follows
FLAGS_tpu_default_matmul_precision.
"""
from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from .registry import register_op


@register_op("matmul", amp_list="white")
def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


@register_op("bmm", amp_list="white")
def bmm(x, y):
    return jnp.matmul(x, y)


@register_op("mm", amp_list="white")
def mm(x, y):
    return jnp.matmul(x, y)


@register_op("dot")
def dot(x, y):
    return jnp.sum(x * y, axis=-1)


@register_op("outer")
def outer(x, y):
    return jnp.outer(x, y)


@register_op("inner")
def inner(x, y):
    return jnp.inner(x, y)


@register_op("cross")
def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


@register_op("t", inplace_view=True)
def t(x):
    if x.ndim < 2:
        return x
    return jnp.swapaxes(x, -1, -2)


@register_op("norm", amp_list="black")
def norm(x, p="fro", axis=None, keepdim=False):
    if axis is None and p in ("fro", 2):
        return jnp.sqrt(jnp.sum(jnp.square(x)))
    if isinstance(axis, (list, tuple)) and len(axis) == 2:
        return jnp.linalg.norm(x, ord=p if p != "fro" else "fro",
                               axis=tuple(axis), keepdims=keepdim)
    if p == "fro":
        p = 2
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.power(
        jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim), 1.0 / p
    )


@register_op("einsum", amp_list="white")
def einsum(operands, equation):
    return jnp.einsum(equation, *list(operands))


@register_op("cholesky", amp_list="black")
def cholesky(x, upper=False):
    l = jnp.linalg.cholesky(x)
    return jnp.swapaxes(l, -1, -2) if upper else l


@register_op("qr", multi_output=True, amp_list="black")
def qr(x, mode="reduced"):
    return tuple(jnp.linalg.qr(x, mode=mode))


@register_op("svd", multi_output=True, amp_list="black")
def svd(x, full_matrices=False):
    u, s, vh = jnp.linalg.svd(x, full_matrices=full_matrices)
    return u, s, jnp.swapaxes(vh, -1, -2)


@register_op("inverse", amp_list="black")
def inverse(x):
    return jnp.linalg.inv(x)


@register_op("pinv", amp_list="black")
def pinv(x, rcond=1e-15):
    return jnp.linalg.pinv(x, rtol=rcond)


@register_op("det", amp_list="black")
def det(x):
    return jnp.linalg.det(x)


@register_op("slogdet", multi_output=True, amp_list="black")
def slogdet(x):
    sign, logabs = jnp.linalg.slogdet(x)
    return sign, logabs


@register_op("matrix_power", amp_list="black")
def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


@register_op("eigh", multi_output=True, amp_list="black")
def eigh(x, UPLO="L"):
    w, v = jnp.linalg.eigh(x, UPLO=UPLO)
    return w, v


@register_op("solve", amp_list="black")
def solve(x, y):
    return jnp.linalg.solve(x, y)


@register_op("triangular_solve", amp_list="black")
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return lax.linalg.triangular_solve(
        x, y, left_side=True, lower=not upper,
        transpose_a=transpose, unit_diagonal=unitriangular,
    )


@register_op("lstsq", multi_output=True, amp_list="black")
def lstsq(x, y, rcond=None):
    sol, res, rank, sv = jnp.linalg.lstsq(x, y, rcond=rcond)
    return sol, res, rank, sv


@register_op("matrix_rank", amp_list="black")
def matrix_rank(x, tol=None):
    return jnp.linalg.matrix_rank(x, rtol=tol)


@register_op("cond", amp_list="black")
def cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


@register_op("histogram")
def histogram(x, bins=100, min=0.0, max=0.0):
    rng = None if (min == 0.0 and max == 0.0) else (min, max)
    hist, _ = jnp.histogram(x, bins=bins, range=rng)
    return hist


@register_op("mv", amp_list="white")
def mv(x, vec):
    return jnp.matmul(x, vec)


@register_op("trace_op")
def trace_op(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)
