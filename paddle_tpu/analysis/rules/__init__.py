"""Rule registry: one module per hazard class, all pure-AST."""
from typing import Dict, List

from ..core import Rule
from .swallowed_api import SwallowedApiRule
from .stale_capture import StaleCaptureRule
from .traced_branch import TracedBranchRule
from .host_sync import HostSyncRule
from .wallclock_replay import WallclockInReplayRule
from .jit_cache_key import JitCacheKeyRule
from .donated_reuse import DonatedReuseRule
from .key_reuse import KeyReuseRule
from .collective_mesh import CollectiveMeshRule
from .metric_cardinality import MetricCardinalityRule
from .state_revert import StateRevertRule

_RULES: List[Rule] = [
    SwallowedApiRule(),
    StaleCaptureRule(),
    TracedBranchRule(),
    HostSyncRule(),
    WallclockInReplayRule(),
    JitCacheKeyRule(),
    # the v2 serving-contract pack (project call graph + dataflow)
    DonatedReuseRule(),
    KeyReuseRule(),
    CollectiveMeshRule(),
    MetricCardinalityRule(),
    StateRevertRule(),
]


def all_rules() -> List[Rule]:
    return list(_RULES)


def get_rule(name: str) -> Rule:
    wanted = name.upper()
    for rule in _RULES:
        if wanted in {c.upper() for c in rule.codes}:
            return rule
    known = ", ".join(r.name for r in _RULES)
    raise KeyError(f"unknown rule {name!r} (known: {known})")
