"""Worker for the two-process DP TRAIN test (verdict r3 #5; SURVEY §2.3
closing ¶, §7 hard part #5 — data pipeline at pod scale).

Each process owns ONE cpu device. The full multi-controller DP recipe:
init_parallel_env (jax.distributed via the PADDLE_* env contract) ->
per-host DataLoader over a DistributedBatchSampler shard ->
jax.make_array_from_process_local_data assembling the global batch ->
ONE jitted functional train step (forward + MSE + grads + Adam) with the
batch sharded over dp and params/optimizer state replicated — XLA emits
the cross-host gradient all-reduce. Prints the per-step losses; the parent
asserts both ranks agree and that the numbers match a single-process run
over the same global batches.
"""
# ALL process-level side effects (env clobber, backend pin, distributed
# init) are gated on __main__: the pytest parent imports this module for
# the model/dataset definitions and must not have its 8-device XLA_FLAGS
# or dist-env state overwritten
if __name__ == "__main__":
    from _device_env import ensure_fake_devices

    ensure_fake_devices(1, force=True)
    from paddle_tpu.distributed import env as dist_env

    dist_env.init_parallel_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import nn  # noqa: E402
from paddle_tpu.io import DataLoader, Dataset, DistributedBatchSampler  # noqa: E402
from paddle_tpu.jit.functional import call_functional, extract_state  # noqa: E402

N, IN, OUT = 32, 8, 4
LOCAL_BS, STEPS = 4, 4


class SynthDS(Dataset):
    """Deterministic regression data keyed by index (same on every host)."""

    def __len__(self):
        return N

    def __getitem__(self, i):
        rng = np.random.RandomState(1000 + i)
        x = rng.randn(IN).astype(np.float32)
        y = rng.randn(OUT).astype(np.float32)
        return x, y


def build_model():
    paddle.seed(7)
    return nn.Sequential(nn.Linear(IN, 16), nn.ReLU(), nn.Linear(16, OUT))


def main():
    assert jax.process_count() == 2, jax.process_count()
    rank = jax.process_index()

    model = build_model()
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=model.parameters())
    params, buffers = extract_state(model)
    # host copies: identical on every process (same seed), so replicated
    # in_shardings can place them without cross-host traffic
    params = {k: np.asarray(v) for k, v in params.items()}
    opt_state = jax.tree_util.tree_map(np.asarray,
                                       opt.functional_state(params))

    mesh = Mesh(np.asarray(jax.devices()), ("dp",))
    data_sh = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())
    p_sh = jax.tree_util.tree_map(lambda _: repl, params)
    o_sh = jax.tree_util.tree_map(lambda _: repl, opt_state)

    def train_step(params, opt_state, t, x, y):
        def loss_of(p):
            out, _ = call_functional(model, p, buffers, (x,),
                                     training=True)
            return jnp.mean((out - y) ** 2)

        loss, grads = jax.value_and_grad(loss_of)(params)
        new_params, new_state = opt.functional_step(
            params, grads, opt_state, jnp.float32(0.05), t)
        return loss, new_params, new_state

    step = jax.jit(train_step,
                   in_shardings=(p_sh, o_sh, None, data_sh, data_sh),
                   out_shardings=(repl, p_sh, o_sh))

    ds = SynthDS()
    sampler = DistributedBatchSampler(ds, batch_size=LOCAL_BS,
                                      num_replicas=2, rank=rank,
                                      shuffle=False)
    loader = DataLoader(ds, batch_sampler=sampler)

    t = 0
    for xb, yb in loader:
        t += 1
        if t > STEPS:
            break
        gx = jax.make_array_from_process_local_data(
            data_sh, np.asarray(xb.numpy()))
        gy = jax.make_array_from_process_local_data(
            data_sh, np.asarray(yb.numpy()))
        loss, params, opt_state = step(params, opt_state,
                                       jnp.int32(t), gx, gy)
        print(f"rank={rank} step={t} loss={float(np.asarray(loss)):.6f}",
              flush=True)


def main_hapi():
    """Model.fit ITSELF in the multi-controller regime: per-host DataLoader
    shard in, global arrays assembled inside the fit loop."""
    assert jax.process_count() == 2
    rank = jax.process_index()

    model_net = build_model()
    wrapped = paddle.DataParallel(model_net)
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=model_net.parameters())
    model = paddle.Model(wrapped)
    from paddle_tpu import nn as pnn
    from paddle_tpu.hapi.callbacks import Callback

    model.prepare(optimizer=opt, loss=pnn.MSELoss())

    class PrintLoss(Callback):
        def on_train_batch_end(self, step, logs=None):
            print(f"rank={rank} hapi_step={step + 1} "
                  f"loss={float(np.sum(logs['loss'])):.6f}", flush=True)

    ds = SynthDS()
    sampler = DistributedBatchSampler(ds, batch_size=LOCAL_BS,
                                      num_replicas=2, rank=rank,
                                      shuffle=False)
    loader = DataLoader(ds, batch_sampler=sampler)
    model.fit(loader, epochs=1, num_iters=STEPS, verbose=0,
              callbacks=[PrintLoss()])




# ---------------------------------------------- r5: eval/predict/metrics
NCLS = 4


class ClsDS(Dataset):
    """Deterministic classification data keyed by index."""

    def __len__(self):
        return N

    def __getitem__(self, i):
        rng = np.random.RandomState(2000 + i)
        x = rng.randn(IN).astype(np.float32)
        y = np.int64(i % NCLS)
        return x, y


def build_cls_model():
    paddle.seed(11)
    return nn.Sequential(nn.Linear(IN, 16), nn.ReLU(), nn.Linear(16, NCLS))


def run_hapi_eval(model, rank_loaders):
    """fit + evaluate + predict through paddle.Model; returns printables."""
    train_loader, eval_loader, pred_loader = rank_loaders
    model.fit(train_loader, eval_data=eval_loader, epochs=1,
              num_iters=STEPS, verbose=0)
    logs = model.evaluate(eval_loader, verbose=0)
    preds = model.predict(pred_loader, stack_outputs=True, verbose=0)
    return (float(np.sum(logs["loss"])), float(logs["acc"]),
            float(np.sum(preds[0])), tuple(preds[0].shape))


def main_hapi_eval():
    """VERDICT r4 #4: evaluate/predict/metrics in the multi-controller
    regime — each process feeds its DistributedBatchSampler shard; outputs
    and labels come back replicated so every process updates metrics with
    the full global batch."""
    assert jax.process_count() == 2
    rank = jax.process_index()

    net = build_cls_model()
    wrapped = paddle.DataParallel(net)
    opt = paddle.optimizer.Adam(learning_rate=0.05,
                                parameters=net.parameters())
    model = paddle.Model(wrapped)
    model.prepare(optimizer=opt, loss=nn.CrossEntropyLoss(),
                  metrics=paddle.metric.Accuracy())

    ds = ClsDS()

    def shard_loader():
        sampler = DistributedBatchSampler(ds, batch_size=LOCAL_BS,
                                          num_replicas=2, rank=rank,
                                          shuffle=False)
        return DataLoader(ds, batch_sampler=sampler)

    loss, acc, psum, pshape = run_hapi_eval(
        model, (shard_loader(), shard_loader(), shard_loader()))
    print(f"rank={rank} eval_loss={loss:.6f} acc={acc:.6f} "
          f"pred_sum={psum:.6f} pred_rows={pshape[0]}", flush=True)


if __name__ == "__main__":
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "hapi":
        main_hapi()
    elif len(sys.argv) > 1 and sys.argv[1] == "hapi_eval":
        main_hapi_eval()
    else:
        main()
