"""KV-cache decode throughput microbench (models/generation.py).

Measures tokens/sec for LLaMA-tiny (CPU smoke) or a larger LLaMA config on
TPU, separating prefill latency from steady-state decode. Run directly:

    python benchmarks/generation_bench.py [--cpu]

Prints one JSON line (same convention as bench.py)."""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    force_cpu = "--cpu" in sys.argv
    import jax

    if force_cpu:
        jax.config.update("jax_platforms", "cpu")
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          num_hidden_layers=16, num_attention_heads=16,
                          num_key_value_heads=16, intermediate_size=5504,
                          max_position_embeddings=2048)
        batch, prompt, new = 8, 128, 128
    else:
        cfg = LlamaConfig.tiny()
        batch, prompt, new = 2, 16, 32
    m = LlamaForCausalLM(cfg)
    m.eval()
    ids = paddle.to_tensor(
        np.random.RandomState(0).randint(0, cfg.vocab_size,
                                         (batch, prompt)))

    def timed(n_tokens):
        # warm at the SAME horizon first: generate()'s jit cache keys on
        # (prompt, total), so a different max_new_tokens would recompile
        # inside the timed region
        m.generate(ids, max_new_tokens=n_tokens, temperature=0.0)
        t0 = time.perf_counter()
        out = m.generate(ids, max_new_tokens=n_tokens, temperature=0.0)
        _ = np.asarray(out.numpy())
        return time.perf_counter() - t0

    short = max(2, new // 8)
    t_short = timed(short)
    t_full = timed(new)
    # two horizons, both including one prefill: the difference isolates
    # steady-state decode, the remainder is the prefill
    decode_s_per_tok = max((t_full - t_short) / (new - short), 1e-9)
    prefill_s = max(t_short - short * decode_s_per_tok, 0.0)
    print(json.dumps({
        "metric": "llama_kvcache_decode_tokens_per_sec",
        "value": round(batch / decode_s_per_tok, 1),
        "unit": "tokens/s",
        "detail": {"device": getattr(dev, "device_kind", dev.platform),
                   "batch": batch, "prompt": prompt, "new_tokens": new,
                   "decode_ms_per_token": round(decode_s_per_tok * 1000, 2),
                   "prefill_ms": round(prefill_s * 1000, 2)},
    }))


if __name__ == "__main__":
    main()
