"""Elastic membership + failure detection (ref: python/paddle/distributed/
fleet/elastic/manager.py, upstream layout, unverified — mount empty).

Paddle's ElasticManager keeps node liveness in etcd (heartbeat leases),
emits scale-in/scale-out events, regenerates the trainer endpoint list and
restarts training. The TPU-native single-controller analog keeps the same
state machine over a shared heartbeat directory (no etcd in the image;
files are the store — the launcher and workers already share a filesystem):

- workers call :func:`start_heartbeat` (a daemon thread stamping
  ``worker_<rank>.hb``);
- the :class:`ElasticManager` scans the directory, tracks membership, and
  emits ``JOIN`` / ``DEAD`` / ``LEAVE`` / ``SCALE_UP`` / ``SCALE_DOWN``
  events to registered callbacks;
- ``endpoints()`` regenerates the PADDLE_TRAINER_ENDPOINTS list for the
  surviving membership, the input to a restart-with-new-world cycle.

The fleetrun launcher exposes this via ``--elastic_dir``: its watch loop
scans between child polls and logs membership transitions.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["Event", "ElasticManager", "start_heartbeat"]


class Event:
    JOIN = "join"
    LEAVE = "leave"          # clean exit (heartbeat file removed)
    DEAD = "dead"            # heartbeat timeout — failure detection
    SCALE_UP = "scale_up"
    SCALE_DOWN = "scale_down"

    def __init__(self, kind: str, rank: int, world: List[int]):
        self.kind = kind
        self.rank = rank
        self.world = list(world)

    def __repr__(self):
        return f"Event({self.kind}, rank={self.rank}, world={self.world})"


def _hb_path(job_dir: str, rank: int) -> str:
    return os.path.join(job_dir, f"worker_{rank}.hb")


def start_heartbeat(job_dir: Optional[str] = None,
                    rank: Optional[int] = None,
                    interval: float = 1.0) -> Callable[[], None]:
    """Stamp this worker's heartbeat file on a daemon thread.

    Returns a stop() callable that also REMOVES the file — a clean LEAVE,
    distinct from going silent (DEAD). Reads PADDLE_ELASTIC_DIR /
    PADDLE_TRAINER_ID when args are omitted (the launcher contract).
    """
    job_dir = job_dir or os.environ.get("PADDLE_ELASTIC_DIR")
    if not job_dir:
        return lambda: None   # elasticity not enabled for this job
    rank = int(os.environ.get("PADDLE_TRAINER_ID", 0)) if rank is None \
        else rank
    os.makedirs(job_dir, exist_ok=True)
    path = _hb_path(job_dir, rank)
    stop_evt = threading.Event()

    def beat():
        while not stop_evt.is_set():
            # atomic replace: a scan between truncate and write would read
            # an empty/partial stamp and emit a false DEAD
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(str(time.time()))
            os.replace(tmp, path)
            stop_evt.wait(interval)

    t = threading.Thread(target=beat, daemon=True)
    t.start()

    def stop():
        stop_evt.set()
        t.join(timeout=5)
        try:
            os.remove(path)
        except OSError:
            pass

    return stop


class ElasticManager:
    """Membership tracker + event source over the heartbeat directory."""

    def __init__(self, job_dir: str, np_expected: Optional[int] = None,
                 dead_timeout: float = 5.0,
                 base_endpoint: str = "127.0.0.1:49600"):
        self.job_dir = job_dir
        self.np_expected = np_expected
        self.dead_timeout = dead_timeout
        self.base_endpoint = base_endpoint
        os.makedirs(job_dir, exist_ok=True)
        self._alive: Dict[int, float] = {}    # rank -> last stamp
        self._callbacks: Dict[str, List[Callable]] = {}

    def on(self, kind: str, callback: Callable[[Event], None]):
        self._callbacks.setdefault(kind, []).append(callback)
        return callback

    def _emit(self, events: List[Event]):
        for ev in events:
            for cb in self._callbacks.get(ev.kind, []):
                cb(ev)
        return events

    def scan(self) -> List[Event]:
        """One pass: read heartbeat files, diff against known membership."""
        now = time.time()
        seen: Dict[int, float] = {}
        for name in os.listdir(self.job_dir):
            if not (name.startswith("worker_") and name.endswith(".hb")):
                continue
            rank = int(name[len("worker_"):-len(".hb")])
            try:
                with open(os.path.join(self.job_dir, name)) as f:
                    seen[rank] = float(f.read().strip() or 0)
            except (OSError, ValueError):
                continue

        events: List[Event] = []
        before = set(self._alive)
        # joins
        for rank, stamp in seen.items():
            if rank not in self._alive and now - stamp <= self.dead_timeout:
                self._alive[rank] = stamp
                events.append(Event(Event.JOIN, rank, sorted(self._alive)))
        # clean leaves (file removed) and deads (file stale)
        for rank in list(self._alive):
            if rank not in seen:
                del self._alive[rank]
                events.append(Event(Event.LEAVE, rank, sorted(self._alive)))
            elif now - seen[rank] > self.dead_timeout:
                del self._alive[rank]
                events.append(Event(Event.DEAD, rank, sorted(self._alive)))
            else:
                self._alive[rank] = seen[rank]
        # scale transitions relative to the expected world
        if self.np_expected is not None:
            crossed_up = (len(before) < self.np_expected
                          <= len(self._alive))
            crossed_down = (len(before) >= self.np_expected
                            > len(self._alive))
            if crossed_up:
                events.append(Event(Event.SCALE_UP, -1,
                                    sorted(self._alive)))
            if crossed_down:
                events.append(Event(Event.SCALE_DOWN, -1,
                                    sorted(self._alive)))
        return self._emit(events)

    def membership(self) -> List[int]:
        return sorted(self._alive)

    def is_healthy(self) -> bool:
        return (self.np_expected is None
                or len(self._alive) >= self.np_expected)

    def endpoints(self) -> str:
        """Regenerated PADDLE_TRAINER_ENDPOINTS for the current membership
        (densely re-ranked — the restart-with-new-world input)."""
        host, port = self.base_endpoint.rsplit(":", 1)
        return ",".join(f"{host}:{int(port) + i}"
                        for i, _ in enumerate(self.membership()))
