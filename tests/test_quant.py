"""Quantized serving (ISSUE 15): int8/fp8 paged KV pool + block-scaled
quantized all-reduce.

Contract under test:

- `kv_dtype="fp32"`/`"bf16"` are bit-exact aliases of the legacy
  `cache_dtype` knob AND import zero quantization code (poisoned-module
  pin, like the tp_size=1 zero-touch guarantee);
- `kv_dtype="int8"` carries a bounded-error parity contract: on the
  tiny greedy config the token stream matches fp32 exactly, and EVERY
  quantized execution path — horizon 1/8, chunked prefill, prefix
  cache, tp 1/2, plain vs quantized all-reduce, interpret-mode Pallas
  kernels — produces the SAME stream bit-for-bit (they all read the
  same quantized pool bytes);
- the 1-byte pool holds >= 2x the resident sequences of fp32 for the
  same byte budget (scale slabs included in the accounting);
- page/scale recycling can never leak stale quantized state into a new
  request, and prefix-cache page sharing works unchanged over
  quantized pages (one logical page = data slab + scale slab);
- a tp2-int8 request migrates onto a tp1-int8 survivor bit-identically
  (`adopt_request` fold — the cluster's migration primitive).
"""
import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
from paddle_tpu.serving import ServingEngine
from paddle_tpu.serving import attention as satt
from paddle_tpu.serving.kv_cache import PagedKVCache, PagedLayerCache
from paddle_tpu.serving.quant import (
    dequantize, kv_pool_bytes, quantize_tokens, quantized_psum,
    resolve_kv_dtype,
)

_HAS_FP8 = hasattr(jnp, "float8_e4m3fn")
PROMPT = [5, 6, 7, 8]


@pytest.fixture(scope="module")
def model():
    paddle.seed(1234)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    return m


def _run(model, prompts=(PROMPT,), new_tokens=10, **kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("max_seq_len", 64)
    eng = ServingEngine(model, **kw)
    rids = [eng.add_request(list(p), max_new_tokens=new_tokens)
            for p in prompts]
    out = eng.run()
    return [out[r] for r in rids], eng


# ------------------------------------------------------------ primitives

class TestQuantPrimitives:
    def test_resolve_names(self):
        i8 = resolve_kv_dtype("int8")
        assert i8.storage_dtype == jnp.int8 and i8.qmax == 127.0
        assert i8.storage_itemsize == 1
        if _HAS_FP8:
            f8 = resolve_kv_dtype("fp8")
            assert f8.storage_dtype == jnp.float8_e4m3fn
            assert f8.qmax == 448.0

    def test_unknown_name_rejected(self):
        with pytest.raises(ValueError, match="kv_dtype"):
            resolve_kv_dtype("int4")

    def test_fp8_without_dtype_support_is_a_clear_error(self, monkeypatch):
        """An old jax without float8_e4m3fn must fail at resolve time
        with a message naming the missing dtype, not deep in tracing."""
        monkeypatch.delattr(jnp, "float8_e4m3fn", raising=False)
        with pytest.raises(ValueError, match="float8_e4m3fn"):
            resolve_kv_dtype("fp8")

    def test_compute_dtype_validated(self):
        with pytest.raises(ValueError, match="compute"):
            resolve_kv_dtype("int8", compute_dtype=jnp.float16)
        resolve_kv_dtype("int8", compute_dtype=jnp.float32)
        resolve_kv_dtype("int8", compute_dtype=jnp.bfloat16)

    def test_int8_roundtrip_error_bound(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, 7, 16)) * 3.0,
                        jnp.float32)
        q, scale = quantize_tokens(x, resolve_kv_dtype("int8"))
        assert q.dtype == jnp.int8
        assert scale.shape == x.shape[:-1] + (1,)
        assert scale.dtype == jnp.float32
        dq = np.asarray(dequantize(q, scale))
        # per-slot bound: |err| <= scale/2 = amax/254 elementwise
        amax = np.max(np.abs(np.asarray(x)), axis=-1, keepdims=True)
        assert np.all(np.abs(dq - np.asarray(x)) <= amax / 253.0)

    def test_zero_rows_stay_exactly_zero(self):
        """All-zero slots take scale 1.0 (never 0/0) and round-trip to
        exact zeros — unwritten pool slots must read as zeros too."""
        x = jnp.zeros((2, 5, 8), jnp.float32)
        q, scale = quantize_tokens(x, resolve_kv_dtype("int8"))
        assert np.all(np.asarray(scale) == 1.0)
        assert np.all(np.asarray(dequantize(q, scale)) == 0.0)

    def test_pool_bytes_accounting(self):
        c32 = PagedKVCache(2, 8, 8, 2, 16)
        ci8 = PagedKVCache(2, 8, 8, 2, 16, kv_dtype="int8")
        assert c32.pool_bytes == kv_pool_bytes(
            2, 8, 8, 2, 16, itemsize=4, quantized=False)
        assert ci8.pool_bytes == kv_pool_bytes(
            2, 8, 8, 2, 16, itemsize=1, quantized=True)

    @pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
    def test_quantized_psum_matches_psum(self):
        from paddle_tpu.serving.tp import _shard_map

        mesh = jax.sharding.Mesh(np.array(jax.devices()[:2]), ("tp",))
        P = jax.sharding.PartitionSpec
        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.standard_normal((2, 4, 300)), jnp.float32)

        def reduce_with(fn):
            f = _shard_map(fn, mesh=mesh, in_specs=(P("tp"),),
                           out_specs=P("tp"))
            return np.asarray(jax.jit(f)(x))

        exact = reduce_with(lambda s: jax.lax.psum(s, "tp"))
        quant = reduce_with(lambda s: quantized_psum(s, "tp"))
        # worst case per element: half an int8 step of the block amax
        # per shard -> 2 * amax / 254; amax of N(0,1) over 256 is ~4
        np.testing.assert_allclose(quant, exact, atol=4 * 2 / 254,
                                   rtol=3e-2)


# ------------------------------------------------- knob + validation

class TestEngineKnob:
    def test_fp32_knob_is_the_default_bit_exact(self, model):
        base, _ = _run(model)
        knob, eng = _run(model, kv_dtype="fp32")
        assert knob == base
        assert not eng.cache.quantized
        assert eng.stats()["kv_dtype"] == "fp32"
        assert "quant" not in eng.stats()

    def test_bf16_knob_matches_legacy_cache_dtype(self, model):
        legacy, _ = _run(model, cache_dtype="bfloat16")
        knob, eng = _run(model, kv_dtype="bf16")
        assert knob == legacy
        assert eng.cache.dtype == jnp.bfloat16
        assert not eng.cache.quantized

    def test_conflicting_knobs_raise(self, model):
        with pytest.raises(ValueError, match="pick one knob"):
            ServingEngine(model, page_size=8, max_seq_len=64,
                          cache_dtype="bfloat16", kv_dtype="int8")

    def test_unknown_kv_dtype_raises(self, model):
        with pytest.raises(ValueError, match="kv_dtype"):
            ServingEngine(model, page_size=8, max_seq_len=64,
                          kv_dtype="int4")

    def test_for_model_validates_name(self, model):
        with pytest.raises(ValueError, match="kv_dtype"):
            PagedKVCache.for_model(model, 8, 8, kv_dtype="nope")

    def test_quantized_pools_carry_scale_slabs(self):
        c = PagedKVCache(2, 8, 8, 2, 16, kv_dtype="int8")
        assert c.quantized and c.kv_dtype == "int8"
        for layer in c.pools:
            assert len(layer) == 4
            kp, vp, ks, vs = layer
            assert kp.dtype == jnp.int8 and vp.dtype == jnp.int8
            assert ks.shape == (2, 8, 8, 1) and ks.dtype == jnp.float32
            # unwritten slots: q=0 everywhere, scale=1 -> dequant 0
            assert np.all(np.asarray(ks) == 1.0)
            assert np.all(np.asarray(vs) == 1.0)

    def test_tp_quantized_allreduce_needs_tp(self, model):
        with pytest.raises(ValueError, match="tp"):
            ServingEngine(model, page_size=8, max_seq_len=64,
                          tp_quantized_allreduce=True)


# ------------------------------------------------------ parity matrix

class TestParityMatrix:
    """One greedy request; every quantized execution path must emit the
    SAME stream (shared quantized pool bytes), and on this config that
    stream matches fp32 token-for-token."""

    @pytest.fixture(scope="class")
    def fp32_stream(self, model):
        streams, _ = _run(model)
        return streams[0]

    @pytest.fixture(scope="class")
    def int8_stream(self, model, fp32_stream):
        streams, eng = _run(model, kv_dtype="int8")
        assert eng.cache.quantized
        assert streams[0] == fp32_stream        # the token-match pin
        return streams[0]

    def test_horizon_1(self, model, int8_stream):
        streams, _ = _run(model, kv_dtype="int8", decode_horizon=1)
        assert streams[0] == int8_stream

    def test_chunked_prefill(self, model, int8_stream):
        streams, _ = _run(model, kv_dtype="int8",
                          enable_chunked_prefill=True)
        assert streams[0] == int8_stream

    def test_prefix_cache(self, model, int8_stream):
        streams, _ = _run(model, kv_dtype="int8",
                          enable_prefix_caching=True)
        assert streams[0] == int8_stream

    @pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
    def test_tp2(self, model, int8_stream):
        streams, _ = _run(model, kv_dtype="int8", tp_size=2)
        assert streams[0] == int8_stream

    @pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
    def test_tp2_quantized_allreduce(self, model, int8_stream):
        streams, eng = _run(model, kv_dtype="int8", tp_size=2,
                            tp_quantized_allreduce=True)
        assert streams[0] == int8_stream
        probe = eng.metrics.get("serving_tp_collective_seconds",
                                labels={"overlap": "off"})
        assert probe is not None and probe.count > 0

    def test_interpret_kernels(self, model, int8_stream, monkeypatch):
        monkeypatch.setattr(satt, "KERNEL_MODE", "interpret")
        streams, _ = _run(model, kv_dtype="int8")
        assert streams[0] == int8_stream

    @pytest.mark.skipif(not _HAS_FP8, reason="no float8_e4m3fn")
    def test_fp8(self, model, fp32_stream):
        """fp8 e4m3 (~2 significant digits) carries only the
        bounded-error contract: the stream may legitimately diverge from
        fp32 after a few tokens, but its first greedy token agrees and
        every fp8 execution path is self-consistent bit-for-bit."""
        streams, eng = _run(model, kv_dtype="fp8")
        assert eng.cache.quantized and eng.cache.kv_dtype == "fp8"
        n = len(PROMPT)
        assert streams[0][:n + 1] == fp32_stream[:n + 1]
        h1, _ = _run(model, kv_dtype="fp8", decode_horizon=1)
        assert h1[0] == streams[0]              # self-consistency

    def test_quant_stats_section(self, model):
        _, eng = _run(model, kv_dtype="int8", new_tokens=2)
        q = eng.stats()["quant"]
        assert q["kv_dtype"] == "int8"
        assert q["pool_bytes"] == eng.cache.pool_bytes
        assert q["fp32_pool_bytes"] > 2 * q["pool_bytes"]


# --------------------------------------------------------- capacity

class TestCapacity:
    def test_int8_holds_at_least_2x_fp32_residency(self):
        """Same byte budget -> >= 2x the pages (hence >= 2x the resident
        sequences the allocator can admit), scale slabs included."""
        c32 = PagedKVCache(2, 8, 8, 2, 16)
        ci8 = PagedKVCache(2, 8, 8, 2, 16, kv_dtype="int8")
        assert c32.page_bytes >= 2 * ci8.page_bytes
        budget = c32.pool_bytes
        assert budget // ci8.page_bytes >= 2 * (budget // c32.page_bytes)

    def test_engine_reports_capacity(self, model):
        _, e32 = _run(model, new_tokens=1)
        _, e8 = _run(model, new_tokens=1, kv_dtype="int8")
        # identical logical geometry, >= 2x cheaper pages
        assert e8.cache.num_pages == e32.cache.num_pages
        assert e32.cache.page_bytes >= 2 * e8.cache.page_bytes


# ---------------------------------------------- zero-import guarantee

class TestZeroImport:
    def _poison(self, monkeypatch):
        def _boom(name):
            raise AssertionError(f"serving.quant touched: {name}")

        poison = types.ModuleType("paddle_tpu.serving.quant")
        poison.__getattr__ = _boom
        monkeypatch.setitem(sys.modules, "paddle_tpu.serving.quant",
                            poison)

    def test_fp32_engine_imports_zero_quant_code(self, model,
                                                 monkeypatch):
        """The default engine must run a FULL request lifecycle without
        touching serving.quant — quantization support is free when
        off."""
        self._poison(monkeypatch)
        streams, _ = _run(model, new_tokens=4)
        assert len(streams[0]) == len(PROMPT) + 4

    def test_int8_engine_does_touch_quant(self, model, monkeypatch):
        self._poison(monkeypatch)
        with pytest.raises(AssertionError, match="quant touched"):
            ServingEngine(model, page_size=8, max_seq_len=64,
                          kv_dtype="int8")


# ----------------------------------- sharing, recycling, migration

class TestQuantizedPages:
    def test_prefix_sharing_over_quantized_pages(self, model):
        """A shared quantized prefix page enters the follower's table at
        refcount 2 (table + radix tree) and the follower's stream is
        identical to a no-cache int8 run — scale slabs shared along with
        the data slabs."""
        shared = list(range(2, 18))             # two full 8-token pages
        follower = shared + [1, 2, 3]
        base, _ = _run(model, prompts=(follower,), new_tokens=6,
                       kv_dtype="int8")
        eng = ServingEngine(model, page_size=8, max_batch_size=4,
                            max_seq_len=64, kv_dtype="int8",
                            enable_prefix_caching=True)
        eng.add_request(shared + [9], max_new_tokens=2)
        eng.run()                               # cold fill of the tree
        rid = eng.add_request(follower, max_new_tokens=6)
        eng.step()                              # follower's prefill
        assert any(v >= 2 for v in eng.cache.allocator._refs.values())
        out = eng.run()
        assert out[rid] == base[0]
        pc = eng.stats()["prefix_cache"]
        assert pc["hit_tokens"] > 0 and pc["hit_rate"] > 0

    def test_recycled_pages_never_leak_stale_scales(self, model):
        """Request A fills quantized pages with data+scales; after A
        frees them, request B reuses the same physical pages. B's stream
        must equal a fresh engine's — every slot B reads was rewritten
        (data AND scale), never inherited."""
        probe = [11, 12, 13, 14, 15]
        fresh, _ = _run(model, prompts=(probe,), new_tokens=8,
                        kv_dtype="int8")
        eng = ServingEngine(model, page_size=8, max_batch_size=4,
                            max_seq_len=64, kv_dtype="int8")
        eng.add_request(list(range(20, 52)), max_new_tokens=8)
        eng.run()                               # fills + frees pages
        assert not eng.cache.allocator._refs    # everything recycled
        rid = eng.add_request(probe, max_new_tokens=8)
        out = eng.run()
        assert out[rid] == fresh[0]

    @pytest.mark.skipif(len(jax.devices()) < 2, reason="needs 2 devices")
    def test_tp2_int8_migrates_onto_tp1_survivor(self, model):
        """Cluster migration across tp degrees with a quantized pool:
        fold prompt+delivered from a tp2-int8 engine into a tp1-int8
        survivor via adopt_request; the continuation must complete the
        exact stream the source would have produced (the journal and
        fold are dtype- and topology-blind)."""
        total = 10
        streams, _ = _run(model, new_tokens=total, kv_dtype="int8",
                          tp_size=2)
        full = streams[0]
        generated = full[len(PROMPT):]
        delivered = generated[:3]
        survivor = ServingEngine(model, page_size=8, max_batch_size=4,
                                 max_seq_len=64, kv_dtype="int8")
        rid = survivor.adopt_request(prompt=PROMPT, delivered=delivered,
                                     max_new_tokens=total, seed=0)
        out = survivor.run()
        # run() echoes the FOLDED prompt (prompt + delivered), then the
        # continuation — together the original stream, exactly once
        assert out[rid] == full
