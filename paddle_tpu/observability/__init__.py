"""paddle_tpu.observability — unified metrics + request-lifecycle
telemetry (TPU-native extension; no upstream paddle counterpart).

Three pieces:

- `metrics`: `MetricsRegistry` of `Counter`/`Gauge`/`Histogram`
  (fixed-log-bucket, p50/p95/p99 estimation) — the single source of
  truth behind `ServingEngine.stats()`; near-zero cost disabled, bounded
  cost enabled;
- `export`: Prometheus text exposition + JSON snapshot round-trip;
- `lifecycle`: `LifecycleTracker` — per-request spans
  (`serving.request[<rid>].<stage>`) folded into the
  paddle_tpu.profiler chrome-trace host tracer;
- `slo`: `SloClass`/`SloTracker` — per-request-class TTFT/TPOT targets,
  goodput counting and sliding-window attainment gauges over the
  existing log-bucket histograms (windowed bucket deltas);
- `flight_recorder`: `FlightRecorder` — bounded ring of control-plane
  events plus JSON post-mortem bundles dumped on engine death /
  quarantine (`tools/postmortem.py` renders them);
- `training`: `TrainingTelemetry`/`DivergenceSentinel` — the ZeRO
  trainer's telemetry plane (ISSUE 19): in-executable health scalars,
  step-phase histograms, divergence sentinel + training postmortems.
  Exported LAZILY (PEP 562) so a telemetry-off process never imports
  it (`ZeroTrainStep` zero-cost-when-off pin).

`global_registry()` is the process-wide registry for library-level
signals (e.g. trace-time paged-attention dispatch counts); each
ServingEngine keeps its OWN registry by default so per-engine stats
never mix.
"""
from __future__ import annotations

import threading
from typing import Optional

from .export import registry_from_snapshot, to_prometheus  # noqa: F401
from .flight_recorder import FlightRecorder, build_postmortem, \
    dump_postmortem  # noqa: F401
from .lifecycle import LifecycleTracker  # noqa: F401
from .metrics import Counter, Gauge, Histogram, MetricsRegistry  # noqa: F401
from .slo import HistogramWindow, SloClass, SloTracker  # noqa: F401

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "LifecycleTracker", "to_prometheus", "registry_from_snapshot",
    "global_registry",
    "SloClass", "SloTracker", "HistogramWindow",
    "FlightRecorder", "build_postmortem", "dump_postmortem",
    "TrainingTelemetry", "TrainingDiverged", "DivergenceSentinel",
    "SentinelConfig",
]

# training-plane symbols resolved lazily (PEP 562): importing the
# package must NOT import observability/training.py — a telemetry-off
# trainer imports zero training-observability code, and the pin in
# tests/test_training_obs.py poisons the submodule to prove it
_LAZY_TRAINING = {
    "TrainingTelemetry", "TrainingDiverged", "DivergenceSentinel",
    "SentinelConfig",
}


def __getattr__(name: str):
    if name in _LAZY_TRAINING:
        from . import training

        return getattr(training, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")

_GLOBAL: Optional[MetricsRegistry] = None
_GLOBAL_LOCK = threading.Lock()


def global_registry() -> MetricsRegistry:
    """Lazily-created process-wide registry (library-level counters that
    have no owning engine)."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                _GLOBAL = MetricsRegistry()
    return _GLOBAL
