"""paddle.inference Config/Predictor API over the StableHLO export
(SURVEY §1 row 12 + §2.1 inference engine row)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import inference, static


@pytest.fixture
def saved_model(tmp_path):
    static.enable_static()
    main, startup = static.Program(), static.Program()
    try:
        with static.program_guard(main, startup):
            x = static.data("x", shape=[None, 4], dtype="float32")
            lin = nn.Linear(4, 2)
            pred = lin(x)
    finally:
        static.disable_static()
    exe = static.Executor()
    exe.run(startup)
    xv = np.random.RandomState(1).randn(5, 4).astype("float32")
    expect, = exe.run(main, feed={"x": xv}, fetch_list=[pred])
    prefix = str(tmp_path / "infer")
    static.save_inference_model(prefix, [x], [pred], exe, program=main)
    return prefix, xv, expect


class TestPredictor:
    def test_handle_roundtrip(self, saved_model):
        prefix, xv, expect = saved_model
        config = inference.Config(prefix)
        predictor = inference.create_predictor(config)
        assert predictor.get_input_names() == ["x"]
        assert len(predictor.get_output_names()) == 1

        h = predictor.get_input_handle("x")
        h.copy_from_cpu(xv)
        predictor.run()
        out = predictor.get_output_handle(
            predictor.get_output_names()[0]).copy_to_cpu()
        np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)

    def test_positional_run_and_dynamic_batch(self, saved_model):
        prefix, xv, expect = saved_model
        predictor = inference.create_predictor(inference.Config(prefix))
        out, = predictor.run([xv])
        np.testing.assert_allclose(out, expect, rtol=1e-5, atol=1e-6)
        # symbolic batch dim: smaller batch on the same compiled artifact
        out2, = predictor.run([xv[:2]])
        np.testing.assert_allclose(out2, expect[:2], rtol=1e-5, atol=1e-6)

    def test_clone_shares_module_not_handles(self, saved_model):
        prefix, xv, _ = saved_model
        p1 = inference.create_predictor(inference.Config(prefix))
        p2 = p1.clone()
        assert p1._model is p2._model
        p1.get_input_handle("x").copy_from_cpu(xv)
        with pytest.raises(RuntimeError, match="not set"):
            p2.run()

    def test_config_surface(self, saved_model):
        prefix, _, _ = saved_model
        c = inference.Config(prefix)
        c.disable_gpu()
        assert not c.use_gpu()
        c.enable_use_gpu(256)
        assert c.use_gpu()
        c.switch_ir_optim(False)
        assert not c.ir_optim()
        c.enable_memory_optim()
        c.set_cpu_math_library_num_threads(4)
        assert "model" in c.summary()

    def test_missing_artifact_raises(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            inference.create_predictor(
                inference.Config(str(tmp_path / "nope")))

    def test_get_version(self):
        assert inference.get_version() == paddle.__version__


def test_config_precision_changes_executed_artifact(tmp_path):
    """VERDICT r4 weak #7: a Config-requested precision must change what
    RUNS, not just a recorded flag. The bf16 module computes in bfloat16
    (its MLIR contains bf16 dots) and its outputs differ from the f32
    module by bf16 rounding — small but nonzero on a deep enough chain."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import inference, static

    paddle.seed(7)
    static.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [-1, 32], "float32")
            h = paddle.static.nn.fc(x, 64, activation="relu")
            y = paddle.static.nn.fc(h, 8)
        exe = static.Executor()
        exe.run(startup)
        path = str(tmp_path / "prec_model")
        static.save_inference_model(path, [x], [y], exe, program=main)
    finally:
        static.disable_static()

    rng = np.random.RandomState(0)
    inp = rng.randn(4, 32).astype("float32") * 3

    cfg32 = inference.Config(path)
    p32 = inference.create_predictor(cfg32)
    out32 = p32.run([inp])[0]

    cfg16 = inference.Config(path)
    cfg16.set_precision(inference.PrecisionType.Bfloat16)
    p16 = inference.create_predictor(cfg16)
    out16 = p16.run([inp])[0]

    # the bf16 artifact is genuinely different compute
    assert "bf16" in p16._model._exported.mlir_module()
    assert "bf16" not in p32._model._exported.mlir_module()
    diff = np.abs(out32 - out16).max()
    assert 0 < diff < 0.5, diff       # bf16 rounding, not garbage
    np.testing.assert_allclose(out16, out32, rtol=0.1, atol=0.2)
