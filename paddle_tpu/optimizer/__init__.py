"""Optimizers (ref: python/paddle/optimizer/, upstream layout, unverified).

Design: each optimizer defines a *pure* per-parameter update rule
(`_apply_update`). The eager `step()` runs one jitted function over the whole
parameter pytree (single XLA dispatch per step — the analog of Paddle's fused
optimizer kernels), and jitted training paths (hapi/fleet) call
`functional_step` with explicit state, so numerics are identical in both
modes. State lives in `_accumulators[param_name][slot]` as jax arrays.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.flags import get_flag
from ..core.tensor import Parameter, Tensor
from . import lr as lr_mod
from .lr import LRScheduler

__all__ = [
    "Optimizer", "SGD", "Momentum", "Adagrad", "Adam", "AdamW", "Adamax",
    "AdamWDL", "RMSProp", "Adadelta", "Lamb", "LRScheduler", "lr",
    "Rprop", "ASGD", "LBFGS", "NAdam", "RAdam",
]

lr = lr_mod


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)


class Optimizer:
    _slot_names: List[str] = []

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        if parameters is None:
            raise ValueError(
                "parameters is required in dygraph mode "
                "(pass model.parameters())")
        # param groups support
        self._param_groups = []
        if parameters and isinstance(parameters[0], dict):
            flat = []
            for group in parameters:
                g = dict(group)
                g["params"] = list(group["params"])
                flat.extend(g["params"])
                self._param_groups.append(g)
            self._parameter_list = flat
        else:
            self._parameter_list = list(parameters)
            self._param_groups = [{"params": self._parameter_list}]
        self._learning_rate = learning_rate
        self.regularization = None
        if isinstance(weight_decay, float):
            self.regularization = L2Decay(weight_decay)
        elif weight_decay is not None:
            self.regularization = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: Dict[str, Dict[str, jax.Array]] = {}
        self._step_count = 0
        self._name = name
        self._param_name_cache = {}
        self._jit_cache = {}

    # ----------------------------------------------------------------- hooks
    def _create_accumulators(self, p_data) -> Dict[str, jax.Array]:
        return {}

    def _apply_update(self, p, g, acc: Dict, lr_val, t, lr_scale=1.0):
        """Pure: (param, grad, slots, lr, step) -> (new_param, new_slots)."""
        raise NotImplementedError

    def _decoupled_decay(self) -> float:
        """AdamW-style decoupled weight decay coefficient (0 = coupled)."""
        return 0.0

    # ------------------------------------------------------------------- lr
    def get_lr(self) -> float:
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate.last_lr
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # ------------------------------------------------------------- step path
    def _param_name(self, p: Parameter) -> str:
        key = id(p)
        if key not in self._param_name_cache:
            name = p.name or f"param_{len(self._param_name_cache)}"
            if name in {v for v in self._param_name_cache.values()}:
                name = f"{name}_{len(self._param_name_cache)}"
            self._param_name_cache[key] = name
        return self._param_name_cache[key]

    def _ensure_accumulators(self, p: Parameter):
        name = self._param_name(p)
        if name not in self._accumulators:
            acc = self._create_accumulators(p._data)
            if self._multi_precision and jnp.issubdtype(
                    p._data.dtype, jnp.floating) and \
                    p._data.dtype != jnp.float32:
                acc["master_weight"] = p._data.astype(jnp.float32)
            self._accumulators[name] = acc
        return self._accumulators[name]

    def _update_tree(self, p_datas, g_datas, accs, lr_val, t, lr_scales,
                     coupled_wd, decoupled_wd, clip_fn):
        # 1. coupled regularization (L2 adds wd*p to grad)
        if coupled_wd:
            g_datas = [g + coupled_wd * p.astype(g.dtype)
                       for p, g in zip(p_datas, g_datas)]
        # 2. gradient clipping
        if clip_fn is not None:
            g_datas = clip_fn(g_datas)
        # 3. per-param update
        new_ps, new_accs = [], []
        for p, g, acc, s in zip(p_datas, g_datas, accs, lr_scales):
            master = acc.pop("master_weight", None)
            work_p = master if master is not None else p
            if decoupled_wd:
                work_p = work_p * (1.0 - lr_val * decoupled_wd)
            np_, nacc = self._apply_update(work_p, g.astype(jnp.float32)
                                          if master is not None else g,
                                          acc, lr_val, t, lr_scale=s)
            if master is not None:
                nacc["master_weight"] = np_
                np_ = np_.astype(p.dtype)
            new_ps.append(np_)
            new_accs.append(nacc)
        return new_ps, new_accs

    def step(self):
        params = [p for p in self._parameter_list
                  if p.trainable and p.grad is not None]
        if not params:
            self._post_step()
            return
        self._step_count += 1
        for p in params:
            self._ensure_accumulators(p)
        names = [self._param_name(p) for p in params]
        p_datas = [p._data for p in params]
        g_datas = [p.grad._data for p in params]
        accs = [dict(self._accumulators[n]) for n in names]
        lr_scales = tuple(p.optimize_attr.get("learning_rate", 1.0)
                          for p in params)
        coupled = self.regularization.coeff if isinstance(
            self.regularization, L2Decay) else 0.0
        decoupled = self._decoupled_decay()
        clip_fn = self._grad_clip._clip_fn() if self._grad_clip is not None \
            else None

        cache_key = (tuple((d.shape, str(d.dtype)) for d in p_datas),
                     lr_scales, bool(clip_fn))
        if cache_key not in self._jit_cache:
            def jitted(p_list, g_list, acc_list, lr_val, t):
                return self._update_tree(p_list, g_list, acc_list, lr_val, t,
                                         lr_scales, coupled, decoupled,
                                         clip_fn)

            self._jit_cache[cache_key] = jax.jit(jitted)
        lr_val = jnp.asarray(self.get_lr(), dtype=jnp.float32)
        t = jnp.asarray(self._step_count, dtype=jnp.int32)
        new_ps, new_accs = self._jit_cache[cache_key](
            p_datas, g_datas, accs, lr_val, t)
        for p, name, np_, nacc in zip(params, names, new_ps, new_accs):
            p._data = np_
            self._accumulators[name] = nacc
        self._post_step()

    def _post_step(self):
        pass

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list:
            p.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static.program import Variable, default_main_program, \
            in_static_mode

        if in_static_mode() and isinstance(loss, Variable):
            # static path: record the update; the Executor compiles the full
            # train step (forward + jax.grad + functional optimizer update)
            # on first run — the meta-optimizer seam (SURVEY §3.2)
            program = default_main_program()
            program._minimize_hooks.append(
                (self, loss, (parameters, no_grad_set)))
            params = parameters or self._parameter_list
            return None, [(p, f"{getattr(p, 'name', 'param')}@GRAD")
                          for p in params]
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._parameter_list]

    # -------------------------------------------------- functional (jit) API
    def functional_state(self, params_dict):
        """Initial optimizer state for a functional train step:
        {param_name: {slot: array}}"""
        state = {}
        for name, data in params_dict.items():
            acc = self._create_accumulators(data)
            if self._multi_precision and jnp.issubdtype(
                    data.dtype, jnp.floating) and data.dtype != jnp.float32:
                acc["master_weight"] = data.astype(jnp.float32)
            state[name] = acc
        return state

    def functional_step(self, params_dict, grads_dict, state, lr_val, t):
        """Pure: used inside jitted train steps (hapi/fleet). Applies
        regularization, clipping and the update rule exactly as step()."""
        names = list(params_dict.keys())
        p_datas = [params_dict[n] for n in names]
        g_datas = [grads_dict[n] for n in names]
        accs = [dict(state[n]) for n in names]
        coupled = self.regularization.coeff if isinstance(
            self.regularization, L2Decay) else 0.0
        clip_fn = self._grad_clip._clip_fn() if self._grad_clip is not None \
            else None
        new_ps, new_accs = self._update_tree(
            p_datas, g_datas, accs, lr_val, t, (1.0,) * len(names), coupled,
            self._decoupled_decay(), clip_fn)
        return (dict(zip(names, new_ps)),
                {n: a for n, a in zip(names, new_accs)})

    # ------------------------------------------------------------ state dict
    def state_dict(self):
        out = {}
        for pname, acc in self._accumulators.items():
            for slot, arr in acc.items():
                out[f"{pname}.{slot}"] = Tensor(arr)
        out["@step_count"] = self._step_count
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state_dict):
        self._step_count = int(state_dict.get("@step_count", 0))
        if "LR_Scheduler" in state_dict and isinstance(
                self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])
        for key, val in state_dict.items():
            if key in ("@step_count", "LR_Scheduler"):
                continue
            pname, slot = key.rsplit(".", 1)
            arr = val._data if isinstance(val, Tensor) else jnp.asarray(
                np.asarray(val))
            self._accumulators.setdefault(pname, {})[slot] = arr

    def _accumulators_for(self, p):
        return self._ensure_accumulators(p)


class SGD(Optimizer):
    def _apply_update(self, p, g, acc, lr_val, t, lr_scale=1.0):
        return p - (lr_val * lr_scale) * g.astype(p.dtype), acc


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, p_data):
        return {"velocity": jnp.zeros_like(
            p_data, dtype=jnp.float32 if self._multi_precision
            else p_data.dtype)}

    def _apply_update(self, p, g, acc, lr_val, t, lr_scale=1.0):
        g = g.astype(p.dtype)
        v = self._momentum * acc["velocity"].astype(p.dtype) + g
        if self._use_nesterov:
            new_p = p - (lr_val * lr_scale) * (g + self._momentum * v)
        else:
            new_p = p - (lr_val * lr_scale) * v
        return new_p, {"velocity": v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _create_accumulators(self, p_data):
        return {"moment": jnp.full_like(p_data, self._init_acc,
                                        dtype=jnp.float32)}

    def _apply_update(self, p, g, acc, lr_val, t, lr_scale=1.0):
        g32 = g.astype(jnp.float32)
        m = acc["moment"] + jnp.square(g32)
        new_p = p - ((lr_val * lr_scale) * g32 /
                     (jnp.sqrt(m) + self._epsilon)).astype(p.dtype)
        return new_p, {"moment": m}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._amsgrad = amsgrad

    def _create_accumulators(self, p_data):
        acc = {
            "moment1": jnp.zeros_like(p_data, dtype=jnp.float32),
            "moment2": jnp.zeros_like(p_data, dtype=jnp.float32),
        }
        if self._amsgrad:
            acc["moment2_max"] = jnp.zeros_like(p_data, dtype=jnp.float32)
        return acc

    def _apply_update(self, p, g, acc, lr_val, t, lr_scale=1.0):
        g32 = g.astype(jnp.float32)
        m1 = self._beta1 * acc["moment1"] + (1 - self._beta1) * g32
        m2 = self._beta2 * acc["moment2"] + (1 - self._beta2) * \
            jnp.square(g32)
        t_f = t.astype(jnp.float32)
        bc1 = 1.0 - jnp.power(self._beta1, t_f)
        bc2 = 1.0 - jnp.power(self._beta2, t_f)
        m1_hat = m1 / bc1
        if self._amsgrad:
            m2_max = jnp.maximum(acc["moment2_max"], m2)
            m2_hat = m2_max / bc2
            new_acc = {"moment1": m1, "moment2": m2, "moment2_max": m2_max}
        else:
            m2_hat = m2 / bc2
            new_acc = {"moment1": m1, "moment2": m2}
        upd = (lr_val * lr_scale) * m1_hat / (jnp.sqrt(m2_hat) +
                                              self._epsilon)
        return (p.astype(jnp.float32) - upd).astype(p.dtype), new_acc


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad, name=name)
        self._wd_coeff = float(weight_decay) if isinstance(
            weight_decay, (int, float)) else weight_decay.coeff
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled_decay(self):
        return self._wd_coeff


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, p_data):
        return {"moment": jnp.zeros_like(p_data, dtype=jnp.float32),
                "inf_norm": jnp.zeros_like(p_data, dtype=jnp.float32)}

    def _apply_update(self, p, g, acc, lr_val, t, lr_scale=1.0):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * acc["moment"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * acc["inf_norm"], jnp.abs(g32))
        t_f = t.astype(jnp.float32)
        lr_t = (lr_val * lr_scale) / (1.0 - jnp.power(self._beta1, t_f))
        new_p = (p.astype(jnp.float32) -
                 lr_t * m / (u + self._epsilon)).astype(p.dtype)
        return new_p, {"moment": m, "inf_norm": u}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _create_accumulators(self, p_data):
        acc = {"mean_square": jnp.zeros_like(p_data, dtype=jnp.float32),
               "momentum": jnp.zeros_like(p_data, dtype=jnp.float32)}
        if self._centered:
            acc["mean_grad"] = jnp.zeros_like(p_data, dtype=jnp.float32)
        return acc

    def _apply_update(self, p, g, acc, lr_val, t, lr_scale=1.0):
        g32 = g.astype(jnp.float32)
        ms = self._rho * acc["mean_square"] + (1 - self._rho) * \
            jnp.square(g32)
        if self._centered:
            mg = self._rho * acc["mean_grad"] + (1 - self._rho) * g32
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
            new_acc = {"mean_square": ms, "mean_grad": mg}
        else:
            denom = jnp.sqrt(ms + self._epsilon)
            new_acc = {"mean_square": ms}
        mom = self._momentum * acc["momentum"] + \
            (lr_val * lr_scale) * g32 / denom
        new_acc["momentum"] = mom
        return (p.astype(jnp.float32) - mom).astype(p.dtype), new_acc


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        self._epsilon = epsilon
        self._rho = rho

    def _create_accumulators(self, p_data):
        return {"avg_squared_grad": jnp.zeros_like(p_data,
                                                   dtype=jnp.float32),
                "avg_squared_update": jnp.zeros_like(p_data,
                                                     dtype=jnp.float32)}

    def _apply_update(self, p, g, acc, lr_val, t, lr_scale=1.0):
        g32 = g.astype(jnp.float32)
        asg = self._rho * acc["avg_squared_grad"] + \
            (1 - self._rho) * jnp.square(g32)
        upd = g32 * jnp.sqrt(acc["avg_squared_update"] + self._epsilon) / \
            jnp.sqrt(asg + self._epsilon)
        asu = self._rho * acc["avg_squared_update"] + \
            (1 - self._rho) * jnp.square(upd)
        new_p = (p.astype(jnp.float32) - (lr_val * lr_scale) * upd).astype(
            p.dtype)
        return new_p, {"avg_squared_grad": asg, "avg_squared_update": asu}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lamb_wd = lamb_weight_decay

    def _create_accumulators(self, p_data):
        return {"moment1": jnp.zeros_like(p_data, dtype=jnp.float32),
                "moment2": jnp.zeros_like(p_data, dtype=jnp.float32)}

    def _apply_update(self, p, g, acc, lr_val, t, lr_scale=1.0):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m1 = self._beta1 * acc["moment1"] + (1 - self._beta1) * g32
        m2 = self._beta2 * acc["moment2"] + (1 - self._beta2) * \
            jnp.square(g32)
        t_f = t.astype(jnp.float32)
        m1_hat = m1 / (1.0 - jnp.power(self._beta1, t_f))
        m2_hat = m2 / (1.0 - jnp.power(self._beta2, t_f))
        r = m1_hat / (jnp.sqrt(m2_hat) + self._epsilon) + \
            self._lamb_wd * p32
        w_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = (p32 - (lr_val * lr_scale) * trust * r).astype(p.dtype)
        return new_p, {"moment1": m1, "moment2": m2}


AdamWDL = AdamW  # incubate alias


class Rprop(Optimizer):
    """Resilient backprop (ref: python/paddle/optimizer/rprop.py, upstream
    layout, unverified — mount empty): per-element step sizes grown/shrunk
    by gradient-sign agreement; full-batch method (sign-based, so the
    gradient magnitude never enters the update)."""

    def __init__(self, learning_rate=0.001,
                 learning_rate_range=(1e-5, 50.0), parameters=None,
                 etas=(0.5, 1.2), grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas

    def _create_accumulators(self, p_data):
        return {"prev_grad": jnp.zeros_like(p_data, dtype=jnp.float32),
                "step_size": jnp.full_like(p_data, float(self.get_lr()),
                                           dtype=jnp.float32)}

    def _apply_update(self, p, g, acc, lr_val, t, lr_scale=1.0):
        g32 = g.astype(jnp.float32)
        sign = jnp.sign(g32 * acc["prev_grad"])
        factor = jnp.where(sign > 0, self._eta_pos,
                           jnp.where(sign < 0, self._eta_neg, 1.0))
        step = jnp.clip(acc["step_size"] * factor, self._lr_min,
                        self._lr_max)
        # on sign flip the step is retracted (grad treated as 0 this round)
        g_eff = jnp.where(sign < 0, 0.0, g32)
        new_p = (p.astype(jnp.float32)
                 - jnp.sign(g_eff) * step).astype(p.dtype)
        return new_p, {"prev_grad": g_eff, "step_size": step}


class ASGD(Optimizer):
    """Averaged SGD (ref: python/paddle/optimizer/asgd.py, upstream layout,
    unverified — mount empty): SGD steps plus a running average of the
    iterates; the average is what `paddle.incubate` ModelAverage exposes
    for eval, here kept as an accumulator slot per the upstream kernel."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._batch_num = batch_num

    def _create_accumulators(self, p_data):
        return {"d": jnp.zeros_like(p_data, dtype=jnp.float32),
                "ys": jnp.zeros((self._batch_num,) + tuple(p_data.shape),
                                jnp.float32)}

    def _apply_update(self, p, g, acc, lr_val, t, lr_scale=1.0):
        # upstream ASGD kernel: d += g_new - ys[t % m]; ys[t % m] = g_new;
        # p -= lr/m * d   (a trailing average over the last m gradients)
        g32 = g.astype(jnp.float32)
        idx = (t - 1) % self._batch_num
        old = acc["ys"][idx]
        d = acc["d"] + g32 - old
        ys = acc["ys"].at[idx].set(g32)
        m = jnp.minimum(t.astype(jnp.float32), float(self._batch_num))
        new_p = (p.astype(jnp.float32)
                 - (lr_val * lr_scale) / m * d).astype(p.dtype)
        return new_p, {"d": d, "ys": ys}


from .lbfgs import LBFGS  # noqa: E402,F401


class NAdam(Optimizer):
    """Nesterov-accelerated Adam (Dozat 2016; ref python/paddle/optimizer/
    nadam.py, upstream layout, unverified — mount empty)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._momentum_decay = momentum_decay

    def _create_accumulators(self, p_data):
        return {"moment1": jnp.zeros_like(p_data, dtype=jnp.float32),
                "moment2": jnp.zeros_like(p_data, dtype=jnp.float32),
                # product of mu_1..mu_t rides as a scalar accumulator
                "mu_product": jnp.ones((), jnp.float32)}

    def _apply_update(self, p, g, acc, lr_val, t, lr_scale=1.0):
        g32 = g.astype(jnp.float32)
        t_f = t.astype(jnp.float32)
        psi = 0.96
        mu_t = self._beta1 * (1.0 - 0.5 * jnp.power(
            psi, t_f * self._momentum_decay))
        mu_next = self._beta1 * (1.0 - 0.5 * jnp.power(
            psi, (t_f + 1.0) * self._momentum_decay))
        mu_prod = acc["mu_product"] * mu_t
        m = self._beta1 * acc["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * acc["moment2"] + (1 - self._beta2) * g32 * g32
        m_hat = (mu_next * m / (1.0 - mu_prod * mu_next)
                 + (1.0 - mu_t) * g32 / (1.0 - mu_prod))
        v_hat = v / (1.0 - jnp.power(self._beta2, t_f))
        new_p = (p.astype(jnp.float32) - (lr_val * lr_scale) * m_hat
                 / (jnp.sqrt(v_hat) + self._epsilon)).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v, "mu_product": mu_prod}


class RAdam(Optimizer):
    """Rectified Adam (Liu et al. 2020; ref python/paddle/optimizer/
    radam.py): warms up the adaptive term by the variance-rectification
    factor, falling back to un-adapted SGD-with-momentum while the
    second-moment estimate is too short to trust."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, p_data):
        return {"moment1": jnp.zeros_like(p_data, dtype=jnp.float32),
                "moment2": jnp.zeros_like(p_data, dtype=jnp.float32)}

    def _apply_update(self, p, g, acc, lr_val, t, lr_scale=1.0):
        g32 = g.astype(jnp.float32)
        t_f = t.astype(jnp.float32)
        m = self._beta1 * acc["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * acc["moment2"] + (1 - self._beta2) * g32 * g32
        m_hat = m / (1.0 - jnp.power(self._beta1, t_f))
        rho_inf = 2.0 / (1.0 - self._beta2) - 1.0
        beta2_t = jnp.power(self._beta2, t_f)
        rho_t = rho_inf - 2.0 * t_f * beta2_t / (1.0 - beta2_t)
        # rectification only when the SMA length is > 4 (else momentum SGD)
        r_num = (rho_t - 4.0) * (rho_t - 2.0) * rho_inf
        r_den = (rho_inf - 4.0) * (rho_inf - 2.0) * jnp.maximum(rho_t, 5.0)
        r_t = jnp.sqrt(jnp.maximum(r_num, 0.0) / r_den)
        v_hat = jnp.sqrt(v / (1.0 - beta2_t)) + self._epsilon
        adaptive = r_t * m_hat / v_hat
        plain = m_hat
        upd = jnp.where(rho_t > 4.0, adaptive, plain)
        new_p = (p.astype(jnp.float32)
                 - (lr_val * lr_scale) * upd).astype(p.dtype)
        return new_p, {"moment1": m, "moment2": v}
