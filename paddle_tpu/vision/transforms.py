"""paddle.vision.transforms — numpy-backed image transforms.

Ref: python/paddle/vision/transforms/transforms.py (upstream layout,
unverified — mount empty). Images are HWC uint8/float numpy arrays (the 'cv2'
backend shape); ToTensor converts to CHW float32 scaled to [0,1]. PIL is not a
dependency — everything is numpy, which is also what feeds the TPU host
transfer path.
"""
from __future__ import annotations

import numbers
import random
from typing import Sequence

import numpy as np

from ..core.tensor import Tensor

__all__ = [
    "Compose", "BaseTransform", "ToTensor", "Normalize", "Resize",
    "CenterCrop", "RandomCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
    "RandomResizedCrop", "Pad", "Transpose", "Grayscale",
    "BrightnessTransform", "ContrastTransform", "SaturationTransform",
    "HueTransform", "ColorJitter", "RandomRotation", "RandomErasing",
    "normalize", "to_tensor", "resize", "hflip", "vflip", "crop",
    "center_crop", "pad", "to_grayscale", "adjust_brightness",
    "adjust_contrast", "adjust_hue", "rotate", "erase",
    "affine", "perspective", "RandomAffine", "RandomPerspective",
]


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def _pair(x):
    if isinstance(x, numbers.Number):
        return int(x), int(x)
    return int(x[0]), int(x[1])


# ------------------------------------------------------------------ functional
def to_tensor(pic, data_format="CHW"):
    img = _as_hwc(pic)
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 255.0
    else:
        img = img.astype(np.float32)
    if data_format == "CHW":
        img = np.transpose(img, (2, 0, 1))
    return Tensor(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    is_tensor = isinstance(img, Tensor)
    arr = img.numpy() if is_tensor else np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        arr = (arr - mean.reshape(-1, 1, 1)) / std.reshape(-1, 1, 1)
    else:
        arr = (arr - mean.reshape(1, 1, -1)) / std.reshape(1, 1, -1)
    return Tensor(arr) if is_tensor else arr


def resize(img, size, interpolation="bilinear"):
    """Resize HWC image with numpy (bilinear or nearest)."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, int):
        # shorter side -> size, keep aspect
        if h < w:
            oh, ow = size, max(1, int(round(w * size / h)))
        else:
            oh, ow = max(1, int(round(h * size / w))), size
    else:
        oh, ow = _pair(size)
    if (oh, ow) == (h, w):
        return img
    dtype = img.dtype
    imgf = img.astype(np.float32)
    if interpolation == "nearest":
        ys = np.clip((np.arange(oh) * h / oh).astype(np.int64), 0, h - 1)
        xs = np.clip((np.arange(ow) * w / ow).astype(np.int64), 0, w - 1)
        out = imgf[ys[:, None], xs[None, :]]
    else:  # bilinear, align_corners=False convention
        ys = (np.arange(oh) + 0.5) * h / oh - 0.5
        xs = (np.arange(ow) + 0.5) * w / ow - 0.5
        y0 = np.clip(np.floor(ys).astype(np.int64), 0, h - 1)
        x0 = np.clip(np.floor(xs).astype(np.int64), 0, w - 1)
        y1 = np.clip(y0 + 1, 0, h - 1)
        x1 = np.clip(x0 + 1, 0, w - 1)
        wy = np.clip(ys - y0, 0.0, 1.0)[:, None, None]
        wx = np.clip(xs - x0, 0.0, 1.0)[None, :, None]
        out = (
            imgf[y0[:, None], x0[None, :]] * (1 - wy) * (1 - wx)
            + imgf[y1[:, None], x0[None, :]] * wy * (1 - wx)
            + imgf[y0[:, None], x1[None, :]] * (1 - wy) * wx
            + imgf[y1[:, None], x1[None, :]] * wy * wx
        )
    if dtype == np.uint8:
        out = np.clip(np.round(out), 0, 255).astype(np.uint8)
    else:
        out = out.astype(dtype)
    return out


def hflip(img):
    return _as_hwc(img)[:, ::-1].copy()


def vflip(img):
    return _as_hwc(img)[::-1].copy()


def crop(img, top, left, height, width):
    img = _as_hwc(img)
    return img[top : top + height, left : left + width].copy()


def center_crop(img, output_size):
    img = _as_hwc(img)
    th, tw = _pair(output_size)
    h, w = img.shape[:2]
    top = max(0, (h - th) // 2)
    left = max(0, (w - tw) // 2)
    return crop(img, top, left, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    img = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        pl = pr = pt = pb = int(padding)
    elif len(padding) == 2:
        pl = pr = int(padding[0])
        pt = pb = int(padding[1])
    else:
        pl, pt, pr, pb = (int(p) for p in padding)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kwargs = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(img, ((pt, pb), (pl, pr), (0, 0)), mode=mode, **kwargs)


def to_grayscale(img, num_output_channels=1):
    img = _as_hwc(img).astype(np.float32)
    if img.shape[2] >= 3:
        gray = img[..., 0] * 0.299 + img[..., 1] * 0.587 + img[..., 2] * 0.114
    else:
        gray = img[..., 0]
    gray = gray[:, :, None]
    if num_output_channels == 3:
        gray = np.repeat(gray, 3, axis=2)
    return gray.astype(np.uint8) if _as_hwc(img).dtype == np.uint8 else gray


def adjust_brightness(img, factor):
    arr = _as_hwc(img)
    out = arr.astype(np.float32) * factor
    return _clip_like(out, arr)


def adjust_contrast(img, factor):
    arr = _as_hwc(img)
    mean = arr.astype(np.float32).mean()
    out = (arr.astype(np.float32) - mean) * factor + mean
    return _clip_like(out, arr)


def adjust_hue(img, factor):
    # approximate hue rotation via channel roll mix; exact HSV omitted
    arr = _as_hwc(img).astype(np.float32)
    if arr.shape[2] < 3 or factor == 0:
        return _clip_like(arr, _as_hwc(img))
    rolled = np.roll(arr[..., :3], 1, axis=2)
    out = arr.copy()
    out[..., :3] = arr[..., :3] * (1 - abs(factor)) + rolled * abs(factor)
    return _clip_like(out, _as_hwc(img))


def _inverse_map(img, xin, yin, fill):
    """Nearest-neighbour sample img at float input coords (h, w grids)."""
    h, w = img.shape[:2]
    xi = np.round(xin).astype(np.int64)
    yi = np.round(yin).astype(np.int64)
    valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
    out = np.full_like(img, fill)
    out[valid] = img[yi[valid], xi[valid]]
    return out


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="nearest", fill=0, center=None):
    """Affine transform (paddle.vision.transforms.affine contract):
    rotation + translation + isotropic scale + shear, about the center."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else (
        center[1], center[0])
    if isinstance(shear, numbers.Number):
        shear = (float(shear), 0.0)
    rad = np.deg2rad(angle)
    sx, sy = np.deg2rad(shear[0]), np.deg2rad(shear[1])
    # forward matrix M = T(center) R S Shear T(-center) + translate;
    # build it then invert for output->input mapping
    # torchvision/paddle matrix convention: rot - sy (y-shear direction)
    a = scale * np.cos(rad - sy) / np.cos(sy)
    b = scale * (-np.cos(rad - sy) * np.tan(sx) / np.cos(sy)
                 - np.sin(rad))
    c = scale * np.sin(rad - sy) / np.cos(sy)
    d = scale * (-np.sin(rad - sy) * np.tan(sx) / np.cos(sy)
                 + np.cos(rad))
    M = np.array([[a, b], [c, d]])
    Minv = np.linalg.inv(M)
    tx, ty = translate
    ys, xs = np.mgrid[0:h, 0:w]
    dx = xs - cx - tx
    dy = ys - cy - ty
    xin = Minv[0, 0] * dx + Minv[0, 1] * dy + cx
    yin = Minv[1, 0] * dx + Minv[1, 1] * dy + cy
    return _inverse_map(img, xin, yin, fill)


def _homography(src, dst):
    """8-dof homography mapping src points -> dst points (4 pairs)."""
    A, bv = [], []
    for (x, y), (u, v) in zip(src, dst):
        A.append([x, y, 1, 0, 0, 0, -u * x, -u * y])
        bv.append(u)
        A.append([0, 0, 0, x, y, 1, -v * x, -v * y])
        bv.append(v)
    hcoef = np.linalg.solve(np.asarray(A, np.float64),
                            np.asarray(bv, np.float64))
    return np.append(hcoef, 1.0).reshape(3, 3)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """4-point perspective warp: startpoints (in the input) map to
    endpoints (in the output)."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    # inverse mapping: output coords -> input coords
    Hinv = _homography(endpoints, startpoints)
    ys, xs = np.mgrid[0:h, 0:w]
    denom = Hinv[2, 0] * xs + Hinv[2, 1] * ys + Hinv[2, 2]
    denom = np.where(np.abs(denom) < 1e-9, 1e-9, denom)
    xin = (Hinv[0, 0] * xs + Hinv[0, 1] * ys + Hinv[0, 2]) / denom
    yin = (Hinv[1, 0] * xs + Hinv[1, 1] * ys + Hinv[1, 2]) / denom
    return _inverse_map(img, xin, yin, fill)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate by angle degrees (nearest-neighbour inverse mapping)."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else (
        center[1], center[0])
    rad = np.deg2rad(angle)
    cos, sin = np.cos(rad), np.sin(rad)
    ys, xs = np.mgrid[0:h, 0:w]
    # inverse rotation: output coord -> input coord
    xin = cos * (xs - cx) + sin * (ys - cy) + cx
    yin = -sin * (xs - cx) + cos * (ys - cy) + cy
    return _inverse_map(img, xin, yin, fill)


def erase(img, i, j, h, w, v, inplace=False):
    is_tensor = isinstance(img, Tensor)
    arr = img.numpy() if is_tensor else np.array(img, copy=not inplace)
    if arr.ndim == 3 and is_tensor:  # CHW
        arr[:, i : i + h, j : j + w] = v
    else:
        arr[i : i + h, j : j + w] = v
    return Tensor(arr) if is_tensor else arr


def _clip_like(out, ref):
    if ref.dtype == np.uint8:
        return np.clip(out, 0, 255).astype(np.uint8)
    return out.astype(ref.dtype)


# ------------------------------------------------------------------- classes
class BaseTransform:
    """Transform base: _apply_image hook, keys plumbing kept minimal."""

    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = _pair(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        img = _as_hwc(img)
        if self.padding is not None:
            img = pad(img, self.padding, self.fill, self.padding_mode)
        th, tw = self.size
        h, w = img.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            img = pad(img, (0, 0, max(0, tw - w), max(0, th - h)), self.fill,
                      self.padding_mode)
            h, w = img.shape[:2]
        top = random.randint(0, h - th) if h > th else 0
        left = random.randint(0, w - tw) if w > tw else 0
        return crop(img, top, left, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if random.random() < self.prob else _as_hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if random.random() < self.prob else _as_hwc(img)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = _pair(size)
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = area * random.uniform(*self.scale)
            aspect = np.exp(random.uniform(np.log(self.ratio[0]),
                                           np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if 0 < cw <= w and 0 < ch <= h:
                top = random.randint(0, h - ch)
                left = random.randint(0, w - cw)
                patch = crop(img, top, left, ch, cw)
                return resize(patch, self.size, self.interpolation)
        return resize(center_crop(img, (min(h, w), min(h, w))), self.size,
                      self.interpolation)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.padding_mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.padding_mode)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return np.transpose(_as_hwc(img), self.order)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        arr = _as_hwc(img)
        gray = to_grayscale(arr, 3).astype(np.float32)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return _clip_like(arr.astype(np.float32) * f + gray * (1 - f), arr)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        return adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.ts = [
            BrightnessTransform(brightness), ContrastTransform(contrast),
            SaturationTransform(saturation), HueTransform(hue),
        ]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self.ts[i](img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.kwargs = dict(interpolation=interpolation, expand=expand,
                           center=center, fill=fill)

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return rotate(img, angle, **self.kwargs)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.kwargs = dict(interpolation=interpolation, fill=fill,
                           center=center)

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        h, w = _as_hwc(img).shape[:2]
        tx = ty = 0
        if self.translate is not None:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
        sc = random.uniform(*self.scale) if self.scale is not None else 1.0
        sh = (0.0, 0.0)
        if self.shear is not None:
            shear = self.shear
            if isinstance(shear, numbers.Number):
                shear = (-shear, shear)
            if len(shear) == 2:
                sh = (random.uniform(shear[0], shear[1]), 0.0)
            else:
                sh = (random.uniform(shear[0], shear[1]),
                      random.uniform(shear[2], shear[3]))
        return affine(img, angle=angle, translate=(tx, ty), scale=sc,
                      shear=sh, **self.kwargs)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.kwargs = dict(interpolation=interpolation, fill=fill)

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        h, w = _as_hwc(img).shape[:2]
        d = self.distortion_scale
        half_h, half_w = int(d * h / 2), int(d * w / 2)
        def jitter(x, y, dx, dy):
            return (x + random.randint(0, max(dx, 1) - 1) * (1 if x == 0 else -1),
                    y + random.randint(0, max(dy, 1) - 1) * (1 if y == 0 else -1))
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [jitter(x, y, half_w, half_h) for x, y in start]
        return perspective(img, start, end, **self.kwargs)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        is_tensor = isinstance(img, Tensor)
        shape = img.shape
        h, w = (shape[1], shape[2]) if is_tensor else shape[:2]
        area = h * w
        for _ in range(10):
            target = area * random.uniform(*self.scale)
            aspect = np.exp(random.uniform(np.log(self.ratio[0]),
                                           np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * aspect)))
            ew = int(round(np.sqrt(target / aspect)))
            if eh < h and ew < w:
                i = random.randint(0, h - eh)
                j = random.randint(0, w - ew)
                return erase(img, i, j, eh, ew, self.value, self.inplace)
        return img
