"""Driver benchmark: ERNIE-1.0 pretrain tokens/sec/chip (BASELINE.json metric).

Runs the full framework train step (hapi-style jitted functional step: forward
+ MLM loss + jax.grad + Adam, bf16 autocast) on the available accelerator and
prints ONE JSON line. vs_baseline is measured MFU / 0.40 — the fraction of
the north-star target (no published reference numbers exist; see BASELINE.md).

Short-window design (round-3 postmortem: the TPU tunnel was up ~10 min in a
10-hour session and the round's bench was a CPU fallback):
- the child writes its best-so-far JSON to bench_trace/bench_partial.json
  after EVERY phase, so a mid-run wedge still leaves a TPU number for the
  supervisor to emit;
- phase order front-loads signal: smoke matmul -> Pallas lowering gates
  (flash fwd/bwd, flash+dropout, fused norms — the round-3 hardware-gate
  debt) -> MFU at the round-2 config (batch 32 x seq 512) -> batch sweep ->
  final measurement with a profiler trace;
- the measurement runs in a CHILD process; this supervisor retries a fresh
  child on failure, then falls back to CPU, and ALWAYS emits a JSON line
  (with an "error" field when degraded) and exits 0;
- the child smoke-tests the backend with a tiny compile before the big one
  and has an internal watchdog that emits an error JSON and hard-exits
  rather than hanging.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

METRIC = "ernie1.0_pretrain_tokens_per_sec_per_chip"
UNIT = "tokens/s/chip"
# all bench scratch (partial JSON, profiler trace) lives under
# bench_trace/ — gitignored, so wedged runs never dirty the tree
TRACE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "bench_trace")
PARTIAL_PATH = os.path.join(TRACE_DIR, "bench_partial.json")
# sticky backend-init probe verdict (BENCH_r05): written by a child whose
# probe found the accelerator runtime wedged, read by the supervisor AND
# later children so attempt 2 starts pinned to CPU instead of re-burning
# its budget on the same dead backend; cleared at the start of each
# supervisor run
VERDICT_PATH = os.path.join(TRACE_DIR, "backend_probe_verdict.json")

PEAK_BF16_FLOPS = {
    # device_kind substring -> peak bf16 FLOP/s per chip
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def _log(msg: str) -> None:
    print(f"[bench] {msg}", file=sys.stderr, flush=True)


def _emit(obj: dict) -> None:
    print(json.dumps(obj), flush=True)


def _error_json(err: str) -> dict:
    return {"metric": METRIC, "value": 0.0, "unit": UNIT,
            "vs_baseline": 0.0, "error": err[-2000:]}


def _peak_flops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    for sub, peak in PEAK_BF16_FLOPS.items():
        if sub in kind:
            return peak
    return None


def _write_partial(obj: dict) -> None:
    """Persist the best-so-far result so a later wedge still leaves signal.
    Every write carries the phase ledger, so even a value-less partial
    tells the supervisor how far the child got."""
    if "error" not in obj:
        _PHASE_STATE["best"] = obj
    obj.setdefault("detail", {})["phases_completed"] = \
        list(_PHASE_STATE["completed"])
    try:
        os.makedirs(TRACE_DIR, exist_ok=True)
        with open(PARTIAL_PATH, "w") as f:
            json.dump(obj, f)
            f.write("\n")
    except OSError:
        pass


# ------------------------------------------------------- per-phase watchdog
#
# Round-5 wedge postmortem: the run died under the driver's external
# `timeout` (rc=124) with parsed: null — no JSON, no partial, no culprit
# phase. The global watchdog below still backstops the whole child; this
# tracker additionally re-arms a PER-PHASE timer at every phase boundary,
# and on fire records a partial JSON naming the completed phases and the
# wedged one, emits the same in the error line, and hard-exits — so the
# tail always says WHERE it died, and the supervisor inherits whatever
# phases did complete.

_PHASE_STATE: dict = {"current": "start", "completed": [], "timer": None,
                      "best": None}


def _enter_phase(name: str, budget: float | None = None) -> None:
    import threading

    st = _PHASE_STATE
    if st["current"] != "start":
        st["completed"].append(st["current"])
    st["current"] = name
    if st["timer"] is not None:
        st["timer"].cancel()
    if budget is None:
        budget = float(os.environ.get("BENCH_PHASE_WATCHDOG_SECS", "700"))
    t = threading.Timer(budget, _phase_wedged, (name, budget))
    t.daemon = True
    t.start()
    st["timer"] = t


def _phase_wedged(name: str, budget: float) -> None:
    st = _PHASE_STATE
    msg = (f"phase watchdog: {name!r} exceeded {budget:.0f}s "
           f"(completed: {','.join(st['completed']) or 'none'})")
    _log(msg)
    base = dict(st["best"]) if st["best"] else _error_json(msg)
    base.setdefault("detail", {})["wedged_phase"] = name
    base["detail"]["phases_completed"] = list(st["completed"])
    try:
        os.makedirs(TRACE_DIR, exist_ok=True)
        with open(PARTIAL_PATH, "w") as f:
            json.dump(base, f)
            f.write("\n")
    except OSError:
        pass
    err = _error_json(msg)
    err["detail"] = {"wedged_phase": name,
                     "phases_completed": list(st["completed"])}
    _emit(err)
    os._exit(3)


# --------------------------------------------------------------------------
# child: the actual measurement
# --------------------------------------------------------------------------

def _start_watchdog(seconds: float) -> None:
    """Emit an error JSON and hard-exit if the child wedges (e.g. a PJRT
    transport hang where block_until_ready never returns)."""
    import threading

    def fire():
        _log(f"watchdog fired after {seconds}s — backend wedged")
        _emit(_error_json(f"watchdog: child exceeded {seconds}s"))
        os._exit(3)  # nonzero: supervisor treats the run as failed

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()


def _run_gates(on_tpu: bool) -> dict:
    """Pallas Mosaic-lowering gates: tiny-shape compile+run of every kernel
    whose hardware status is unverified (PERF_NOTES round-3 debt). Each gate
    is independent; failures are recorded, not fatal."""
    import jax.numpy as jnp
    from paddle_tpu.ops import pallas_kernels as pk

    gates: dict[str, str] = {}
    if not on_tpu:
        return _run_aot_gates()
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(1, 256, 4, 64), jnp.bfloat16)  # (b, s, h, d)

    def gate(name, fn):
        t0 = time.perf_counter()
        try:
            fn()
            gates[name] = f"ok ({time.perf_counter() - t0:.1f}s)"
        except Exception as e:  # noqa: BLE001 — gate must record, not die
            gates[name] = f"FAIL {type(e).__name__}: {str(e)[:300]}"
        _log(f"phase=gates: {name}: {gates[name][:80]}")

    def flash_fwd():
        np.asarray(pk._flash_attention_data(q, q, q, is_causal=True))

    def flash_bwd():
        import jax
        g = jax.grad(lambda a: pk._flash_attention_data(
            a, a, a, is_causal=True).astype(jnp.float32).sum())(q)
        np.asarray(g)

    def flash_dropout():
        import jax.numpy as jnp2
        np.asarray(pk._flash_attention_data(
            q, q, q, seed=jnp2.asarray([1234], jnp2.int32),
            is_causal=True, dropout_p=0.1))

    def norms():
        x = jnp.asarray(rng.randn(512, 1024), jnp.bfloat16)
        w = jnp.ones((1024,), jnp.bfloat16)
        np.asarray(pk.rms_norm_fused(x, w))
        np.asarray(pk.layer_norm_fused(x, w, w))

    def ring_step():
        # one ring STEP = _fwd_call with SMEM offsets + pl.when block skip
        # (the new Mosaic surface of the Pallas ring attention); a future
        # block must come back all-masked (zeros + -inf lse)
        kw = dict(scale=0.125, sk=256, is_causal=True, has_mask=False,
                  mask_b_is_one=True, mask_h_is_one=True,
                  mask_q_is_one=True, block_q=128, block_k=128,
                  dropout_p=0.0, interpret=False)
        mask = jnp.zeros((1, 1, 1, 1), jnp.float32)
        sd = jnp.zeros((1,), jnp.int32)
        q2 = q[:, :, :, :64]
        qp = jnp.pad(q2, ((0, 0), (0, 0), (0, 0), (0, 64))).transpose(
            0, 2, 1, 3)
        o, lse = pk._fwd_call(qp, qp, qp, mask, sd,
                              offs=jnp.asarray([0, 4096], jnp.int32),
                              keep_neg_inf_lse=True, **kw)
        assert float(np.max(np.abs(np.asarray(o, np.float32)))) == 0.0
        assert bool(np.all(np.isneginf(np.asarray(lse))))

    def paged_decode():
        # the serving engine's ragged paged-attention decode kernel
        from paddle_tpu.serving import attention as satt

        kvh, hd, ps, pages, maxp, bb = 4, 128, 16, 16, 4, 4
        kp = jnp.asarray(rng.randn(kvh, pages, ps, hd), jnp.bfloat16)
        qq = jnp.asarray(rng.randn(bb, 1, 8, hd), jnp.bfloat16)
        pt = jnp.asarray(rng.randint(1, pages, (bb, maxp)), jnp.int32)
        pos = jnp.asarray([3, 17, 33, 60], jnp.int32)
        np.asarray(satt._paged_decode_pallas(qq, kp, kp, pt, pos))

    def ragged_paged():
        # the unified mixed-step ragged paged-attention kernel: decode
        # rows, a prefill-chunk run, and parked padding in one flat call
        from paddle_tpu.serving import attention as satt

        kvh, hd, ps, pages, maxp, rows, tt = 4, 128, 16, 16, 4, 4, 16
        kp = jnp.asarray(rng.randn(kvh, pages, ps, hd), jnp.bfloat16)
        qq = jnp.asarray(rng.randn(1, tt, 8, hd), jnp.bfloat16)
        pt = jnp.asarray(rng.randint(1, pages, (rows, maxp)), jnp.int32)
        pos = jnp.asarray(np.r_[[5, 17], np.arange(8, 14),
                                np.full(8, maxp * ps)], jnp.int32)
        rid = jnp.asarray(np.r_[[0, 1], np.full(6, 2), np.zeros(8)],
                          jnp.int32)
        np.asarray(satt._ragged_paged_pallas(qq, kp, kp, pt, pos, rid))

    def paged_decode_quant():
        # dequantizing variant: int8 pools + fp32 scale slabs, page_size
        # 32 (the int8 min-tile floor _quant_kernel_ok enforces)
        from paddle_tpu.serving import attention as satt

        kvh, hd, ps, pages, maxp, bb = 4, 128, 32, 16, 2, 4
        kp = jnp.asarray(rng.randint(-127, 128, (kvh, pages, ps, hd)),
                         jnp.int8)
        ks = jnp.asarray(rng.rand(kvh, pages, ps, 1), jnp.float32)
        qq = jnp.asarray(rng.randn(bb, 1, 8, hd), jnp.bfloat16)
        pt = jnp.asarray(rng.randint(1, pages, (bb, maxp)), jnp.int32)
        pos = jnp.asarray([3, 17, 33, 60], jnp.int32)
        np.asarray(satt._paged_decode_pallas(qq, kp, kp, pt, pos,
                                             k_scale=ks, v_scale=ks))

    def ragged_paged_quant():
        from paddle_tpu.serving import attention as satt

        kvh, hd, ps, pages, maxp, rows, tt = 4, 128, 32, 16, 2, 4, 16
        kp = jnp.asarray(rng.randint(-127, 128, (kvh, pages, ps, hd)),
                         jnp.int8)
        ks = jnp.asarray(rng.rand(kvh, pages, ps, 1), jnp.float32)
        qq = jnp.asarray(rng.randn(1, tt, 8, hd), jnp.bfloat16)
        pt = jnp.asarray(rng.randint(1, pages, (rows, maxp)), jnp.int32)
        pos = jnp.asarray(np.r_[[5, 17], np.arange(8, 14),
                                np.full(8, maxp * ps)], jnp.int32)
        rid = jnp.asarray(np.r_[[0, 1], np.full(6, 2), np.zeros(8)],
                          jnp.int32)
        np.asarray(satt._ragged_paged_pallas(qq, kp, kp, pt, pos, rid,
                                             k_scale=ks, v_scale=ks))

    def paged_decode_overlap():
        # the overlap engine's split-collective ring (ISSUE 18): K
        # micro-row ppermute transports interleaved with the consumer
        # matmul, compiled over a real tp mesh — Mosaic must lower the
        # ring schedule itself, not just the serial psum it replaces
        import jax
        from paddle_tpu.parallel.mesh import build_mesh
        from paddle_tpu.serving.overlap import overlap_probe_fn

        ndev = len(jax.devices())
        if ndev < 2:
            raise RuntimeError("split-collective ring needs >= 2 devices")
        mesh = build_mesh((("tp", 4 if ndev >= 4 else 2),))
        x = jnp.asarray(rng.randn(8, 256), jnp.float32)
        np.asarray(jax.jit(overlap_probe_fn(mesh, 256, 2))(x))

    gate("flash_fwd", flash_fwd)
    gate("flash_bwd", flash_bwd)
    gate("flash_dropout", flash_dropout)
    gate("fused_norms", norms)
    gate("ring_step", ring_step)
    gate("paged_decode", paged_decode)
    gate("ragged_paged", ragged_paged)
    gate("paged_decode_quant", paged_decode_quant)
    gate("ragged_paged_quant", ragged_paged_quant)
    gate("paged_decode_overlap", paged_decode_overlap)
    return gates


def _obs_snapshot() -> dict:
    """Process-global observability registry snapshot (trace-time paged
    attention dispatch counts etc.) for the bench JSON — the per-engine
    serving metrics ride inside the serving_prefix/serving_decode phase
    payloads already."""
    try:
        from paddle_tpu.observability import global_registry

        return global_registry().snapshot()
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        return {"error": f"{type(e).__name__}: {str(e)[:200]}"}


def _gen_bench_module():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "generation_bench",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "benchmarks", "generation_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _tiny_serving_model():
    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    paddle.seed(0)
    cfg = LlamaConfig.tiny()
    model = LlamaForCausalLM(cfg)
    model.eval()
    return model, cfg


def _run_lint() -> dict:
    """graftlint phase: the static-analysis gate's JSON report embedded in
    the bench detail, so a hazard count regression shows up next to the
    perf numbers it predicts. Pure AST in a subprocess — no jax, runs
    before the backend comes up. Non-fatal: a failure is recorded, not
    raised (the gate itself is tests/test_lint.py; the bench only
    observes)."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join("tools", "graftlint.py"),
             "paddle_tpu", "--format", "json"],
            capture_output=True, text=True, timeout=120,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        report = json.loads(proc.stdout)
        out = {
            "clean": report["clean"],
            "unbaselined": report["unbaselined_count"],
            "baselined": report["baselined_count"],
            "stale_baseline": report["stale_baseline_count"],
            "by_rule": report["by_rule"],
            # v2 is flow-aware and project-wide: the sweep's wall time is
            # itself a tracked budget (< 3 s on CPU, tests/test_lint_v2.py)
            "sweep_seconds": report.get("sweep_seconds"),
        }
        _log(f"phase=lint: {'clean' if out['clean'] else 'DIRTY'} "
             f"({out['unbaselined']} unbaselined, "
             f"{out['baselined']} baselined, "
             f"sweep {out['sweep_seconds']}s)")
        return out
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        _log(f"phase=lint: FAIL {type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def _run_serving_prefix(on_tpu: bool) -> dict:
    """Shared-system-prompt serving phase: ttft with the prefix cache on
    vs off plus hit rate (benchmarks/generation_bench.py's phase, reused
    here so the driver bench reports cache efficacy alongside MFU).
    Non-fatal: a failure is recorded, not raised."""
    try:
        mod = _gen_bench_module()
        model, cfg = _tiny_serving_model()
        out = mod.serving_prefix_phase(model, cfg, on_tpu)
        _log(f"phase=serving_prefix: ttft {out['ttft_cache_off_ms']}ms -> "
             f"{out['ttft_cache_on_ms']}ms (hit rate {out['hit_rate']})")
        return out
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        _log(f"phase=serving_prefix: FAIL {type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def _run_serving_decode(on_tpu: bool) -> dict:
    """Decode-horizon serving phase: steady-state scheduled decode
    tokens/s and host syncs per token at horizon 1 vs 8 (the fused
    decode+sample block + async overlap). Non-fatal like the phases
    around it."""
    try:
        mod = _gen_bench_module()
        model, cfg = _tiny_serving_model()
        out = mod.serving_decode_phase(model, cfg, on_tpu)
        _log(f"phase=serving_decode: "
             f"{out['horizon_1']['decode_tokens_per_s']} tok/s @h1 -> "
             f"{out['horizon_8']['decode_tokens_per_s']} tok/s @h8 "
             f"(syncs/token {out['horizon_1']['syncs_per_token']} -> "
             f"{out['horizon_8']['syncs_per_token']})")
        return out
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        _log(f"phase=serving_decode: FAIL {type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def _run_serving_tp(on_tpu: bool) -> dict:
    """Tensor-parallel serving phase: the scheduled decode workload at
    tp 1/2/4 with bit-identical-token assertion and the psum-probe
    collective time. A null throughput result on CPU fake devices is
    expected (shards are threads on one chip); the parity bit is the
    CPU-meaningful signal. Non-fatal like the phases around it."""
    try:
        mod = _gen_bench_module()
        model, cfg = _tiny_serving_model()
        out = mod.serving_tp_phase(model, cfg, on_tpu)
        if "skipped" in out:
            _log(f"phase=serving_tp: skipped ({out['skipped']})")
            return out
        degrees = ", ".join(
            f"tp{d}={out[f'tp{d}']['decode_tokens_per_s']} tok/s"
            + (f" (psum probe {out[f'tp{d}']['psum_probe_us']}us)"
               if "psum_probe_us" in out[f"tp{d}"] else "")
            for d in out["degrees"])
        _log(f"phase=serving_tp: {degrees}, "
             f"parity_ok={out['parity_ok']}")
        if not out["parity_ok"]:
            _log("phase=serving_tp: WARN tp token parity FAILED")
        return out
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        _log(f"phase=serving_tp: FAIL {type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def _run_serving_tp_overlap(on_tpu: bool) -> dict:
    """Collective/compute overlap phase: the tp decode workload serial
    vs split-psum ring at chunks 2/4, with the bit-identical-token
    assertion and the measured overlap fraction. overlap_fraction ~0 on
    CPU is the honest null (ring hops are host memcpys with no
    independent interconnect to hide under); parity is the CPU-true
    signal. Non-fatal like the phases around it."""
    try:
        mod = _gen_bench_module()
        model, cfg = _tiny_serving_model()
        out = mod.serving_tp_overlap_phase(model, cfg, on_tpu)
        if "skipped" in out:
            _log(f"phase=serving_tp_overlap: skipped ({out['skipped']})")
            return out
        cells = ", ".join(
            f"tp{d} serial={out[f'tp{d}']['serial']['decode_tokens_per_s']}"
            f" c2={out[f'tp{d}']['chunks2']['decode_tokens_per_s']}"
            f" (ovl {out[f'tp{d}']['chunks2']['overlap_fraction']:.3f})"
            for d in out["degrees"][1:])
        _log(f"phase=serving_tp_overlap: {cells} tok/s, "
             f"parity_ok={out['parity_ok']}")
        if not out["parity_ok"]:
            _log("phase=serving_tp_overlap: WARN overlapped tokens "
                 "diverged from serial engine")
        return out
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        _log(f"phase=serving_tp_overlap: FAIL {type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def _run_serving_spec(on_tpu: bool) -> dict:
    """Speculative-decoding phase: model-free n-gram drafts on vs off
    at horizon 1/8 over repetitive and random prompts — accept rate,
    emitted tokens per target step, greedy-stream parity. tok/s is an
    expected null on CPU (verify flops run serially); the CPU-true
    signal is tokens_per_target_step > 1 on repetitive traffic.
    Non-fatal like the phases around it."""
    try:
        mod = _gen_bench_module()
        model, cfg = _tiny_serving_model()
        out = mod.serving_spec_phase(model, cfg, on_tpu)
        rep, rnd = out["repetitive"]["h8"], out["random"]["h8"]
        _log(f"phase=serving_spec: repetitive h8 "
             f"a={rep['on'].get('accept_rate')} "
             f"t/s={rep['on'].get('tokens_per_target_step')} "
             f"({rep['off']['tok_s']} -> {rep['on']['tok_s']} tok/s), "
             f"random h8 a={rnd['on'].get('accept_rate')} "
             f"t/s={rnd['on'].get('tokens_per_target_step')}, "
             f"parity_ok={rep['parity_ok'] and rnd['parity_ok']}")
        if not (rep["parity_ok"] and rnd["parity_ok"]):
            _log("phase=serving_spec: WARN greedy spec stream diverged "
                 "from non-speculative decoding")
        return out
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        _log(f"phase=serving_spec: FAIL {type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def _run_serving_faults(on_tpu: bool) -> dict:
    """Seeded chaos serving phase: the workload re-runs under a
    FaultInjector schedule (transient dispatch faults, periodic alloc
    faults, one persistent fault, one mid-flight cancel) and asserts
    survivor-token parity against the fault-free run. Non-fatal like
    the phases around it."""
    try:
        mod = _gen_bench_module()
        model, cfg = _tiny_serving_model()
        out = mod.serving_faults_phase(model, cfg, on_tpu)
        _log(f"phase=serving_faults: fired {out['injected']['fired']} "
             f"retries={out['transient_retries']} "
             f"terminal={out['terminal']} "
             f"survivor_parity_ok={out['survivor_parity_ok']} "
             f"chaos_overhead={out['chaos_overhead']}x")
        if not out["survivor_parity_ok"]:
            _log("phase=serving_faults: WARN survivor parity FAILED")
        return out
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        _log(f"phase=serving_faults: FAIL {type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def _run_serving_chunked(on_tpu: bool) -> dict:
    """Long-prompt interference phase: decoders' inter-token p99 and the
    decode-stall histogram with chunked prefill on vs off while one long
    prompt lands mid-decode (head-of-line blocking vs Sarathi-style
    stall-free batching). Non-fatal like the phases around it."""
    try:
        mod = _gen_bench_module()
        model, cfg = _tiny_serving_model()
        out = mod.serving_chunked_phase(model, cfg, on_tpu)
        _log(f"phase=serving_chunked: inter-token p99 "
             f"{out['chunking_off']['inter_token_p99_ms']}ms -> "
             f"{out['chunking_on']['inter_token_p99_ms']}ms, "
             f"stall p99 {out['chunking_off']['decode_stall_p99_ms']}ms "
             f"-> {out['chunking_on']['decode_stall_p99_ms']}ms, "
             f"ttft(long) {out['chunking_off']['ttft_long_ms']}ms -> "
             f"{out['chunking_on']['ttft_long_ms']}ms "
             f"({out['chunking_on']['prefill_chunks']} chunks of "
             f"{out['chunk_tokens']})")
        return out
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        _log(f"phase=serving_chunked: FAIL {type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def _run_serving_ragged(on_tpu: bool) -> dict:
    """Unified ragged mixed-step phase: the chunked-prefill interference
    workload re-run with the single flat Ragged-Paged-Attention
    executable on vs off (both chunked) — bit-identical streams, with
    the per-step launch count collapsing from one-per-chunk-plus-decode
    to one. Non-fatal like the phases around it."""
    try:
        mod = _gen_bench_module()
        model, cfg = _tiny_serving_model()
        out = mod.serving_ragged_phase(model, cfg, on_tpu)
        _log(f"phase=serving_ragged: dispatches/step "
             f"{out['ragged_off']['dispatches_per_step']} -> "
             f"{out['ragged_on']['dispatches_per_step']} "
             f"({out['dispatches_per_step_reduction']}x), tok/s "
             f"{out['ragged_off']['tok_s']} -> "
             f"{out['ragged_on']['tok_s']}, stall p99 "
             f"{out['ragged_off']['decode_stall_p99_ms']}ms -> "
             f"{out['ragged_on']['decode_stall_p99_ms']}ms, "
             f"{out['ragged_on']['ragged_executables']} ragged "
             f"executable(s) over buckets {out['token_buckets']}, "
             f"parity_ok={out['token_parity_ok']}")
        if not out["token_parity_ok"]:
            _log("phase=serving_ragged: WARN ragged-vs-chained token "
                 "parity FAILED")
        return out
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        _log(f"phase=serving_ragged: FAIL {type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def _run_serving_recovery(on_tpu: bool) -> dict:
    """Crash recovery phase: the workload re-runs under an
    EngineSupervisor killed mid-flight by an injected `device_lost`
    fatal (with and without prefix caching on the rebuilt engine) and
    asserts post-restore token parity against the uninterrupted run.
    Non-fatal like the phases around it."""
    try:
        mod = _gen_bench_module()
        model, cfg = _tiny_serving_model()
        out = mod.serving_recovery_phase(model, cfg, on_tpu)
        nc, wc = out["no_prefix_cache"], out["with_prefix_cache"]
        _log(f"phase=serving_recovery: t_recover "
             f"{nc['t_recover_ms']}ms, readmitted {nc['readmitted']}, "
             f"re-prefill tokens {nc['reprefill_tokens_paid']} -> "
             f"{wc['reprefill_tokens_paid']} with prefix cache "
             f"(saved {out['reprefill_saved_by_prefix_cache']}), "
             f"parity_ok={nc['post_restore_parity_ok']}/"
             f"{wc['post_restore_parity_ok']}, "
             f"crash_overhead={out['crash_overhead']}x")
        if not (nc["post_restore_parity_ok"]
                and wc["post_restore_parity_ok"]):
            _log("phase=serving_recovery: WARN post-restore parity "
                 "FAILED")
        return out
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        _log(f"phase=serving_recovery: FAIL {type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def _run_serving_cluster(on_tpu: bool) -> dict:
    """Replicated-cluster phase: a 3-replica ServingCluster loses one
    replica to a seeded `device_lost` mid-workload — reports throughput
    before/during/after the kill, migration latency, and the
    prefix-affinity hit-token payoff vs round-robin routing, asserting
    bit-exact parity against an uninterrupted single engine. Non-fatal
    like the phases around it."""
    try:
        mod = _gen_bench_module()
        model, cfg = _tiny_serving_model()
        out = mod.serving_cluster_phase(model, cfg, on_tpu)
        _log(f"phase=serving_cluster: tok/s "
             f"{out['tok_s_before_kill']} -> {out['tok_s_during_kill']}"
             f" (kill) -> {out['tok_s_after_kill']} (2 replicas), "
             f"{out['migrations']} migration(s) "
             f"({out['migrated_tokens']} folded tokens, "
             f"p50 {out['migration_ms'].get('p50', 0.0)}ms), "
             f"affinity hit tokens {out['affinity_hit_tokens']} vs "
             f"{out['round_robin_hit_tokens']} round-robin, "
             f"parity_ok={out['parity_ok']}")
        if not out["parity_ok"]:
            _log("phase=serving_cluster: WARN replica-loss parity "
                 "FAILED")
        return out
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        _log(f"phase=serving_cluster: FAIL {type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def _run_serving_slo(on_tpu: bool) -> dict:
    """Observability v2 phase: goodput vs raw throughput under two SLO
    classes on mixed load, recorder overhead at typical ring sizes, and
    the post-mortem bundle a seeded `device_lost` kill leaves behind.
    Non-fatal like the phases around it."""
    try:
        mod = _gen_bench_module()
        model, cfg = _tiny_serving_model()
        out = mod.serving_slo_phase(model, cfg, on_tpu)
        worst = max(r["overhead"] for r in out["recorder_ring"].values())
        _log(f"phase=serving_slo: goodput {out['goodput_tokens']}/"
             f"{out['tokens_generated']} tokens "
             f"({out['goodput_fraction']}), interactive ttft attainment "
             f"{out['slo']['interactive']['attainment_ttft']}, recorder "
             f"{out['record_ns_per_event']}ns/event "
             f"(worst ring overhead {worst}x), postmortem "
             f"events={out['postmortem']['events_in_bundle']} "
             f"complete={out['postmortem']['has_fault_and_dead']}")
        if not out["postmortem"]["has_fault_and_dead"]:
            _log("phase=serving_slo: WARN death bundle missing "
                 "fault/dead events")
        return out
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        _log(f"phase=serving_slo: FAIL {type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def _run_serving_quant(on_tpu: bool) -> dict:
    """Quantized-serving phase: pool capacity per byte and decode tok/s
    at fp32/bf16/int8 KV (greedy parity deltas vs fp32), plus the TP
    block-scaled int8 all-reduce probe with qar on/off. Non-fatal like
    the phases around it."""
    try:
        mod = _gen_bench_module()
        model, cfg = _tiny_serving_model()
        out = mod.serving_quant_phase(model, cfg, on_tpu)
        i8 = out["kv"]["int8"]
        _log(f"phase=serving_quant: int8 pool {i8['pool_bytes']}B "
             f"({i8['capacity_ratio']}x fp32 capacity), parity "
             f"token_match={i8['token_match']} tok/s={i8['tok_s']}, "
             f"qar probe {out['tp_psum_probe_us']}")
        if not i8["token_match"]:
            _log("phase=serving_quant: WARN int8 greedy stream diverged "
                 "from fp32 on the tiny config")
        return out
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        _log(f"phase=serving_quant: FAIL {type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def _run_pretrain_zero(on_tpu: bool) -> dict:
    """ZeRO-sharded pretrain phase (ISSUE 16): replicated vs ZeRO-1/2
    at dp 1/2/4 on the parallel substrate — tok/s, optimizer+param
    bytes/chip (the 1/dp claim, asserted exactly), bit-parity vs the
    replicated baseline, analytic max-batch headroom, and the dp
    all-reduce probe. Throughput is an expected null on the CPU
    fake-device mesh (see the phase docstring); non-fatal like the
    phases around it. Since ISSUE 19 the phase also carries a training
    observability leg: telemetry snapshot + sentinel summary, measured
    per-step telemetry overhead (<2% target on real hardware), and a
    deliberate-NaN divergence drill that must dump exactly one
    parseable postmortem bundle. Since ISSUE 20 it also carries the
    bucketed/overlapped schedule sweep: {serial, overlap} x
    bucket_bytes x {fp32, bf16} cells with per-cell tok/s, the
    comm-probe wall times, the measured overlap fraction, and the
    fp32 bit-parity / bf16 bounded-error flags."""
    try:
        mod = _gen_bench_module()
        out = mod.pretrain_zero_phase(on_tpu)
        if "skipped" in out:
            _log(f"phase=pretrain_zero: skipped ({out['skipped']})")
            return out
        dp_max = out["degrees"][-1]
        z1 = out.get(f"dp{dp_max}_stage1", {})
        repl = out.get(f"dp{dp_max}_stage0", {})
        _log(f"phase=pretrain_zero: dp{dp_max} ZeRO-1 "
             f"{z1.get('tok_s')} tok/s vs replicated "
             f"{repl.get('tok_s')}, opt bytes/chip "
             f"{z1.get('opt_bytes_per_chip')} vs "
             f"{repl.get('opt_bytes_per_chip')} "
             f"(1/dp exact={out['opt_bytes_exactly_1_over_dp']}), "
             f"parity_ok={out['parity_ok']}, probe "
             f"{z1.get('dp_allreduce_probe_us')}us")
        if not out["parity_ok"]:
            _log("phase=pretrain_zero: WARN ZeRO params diverged from "
                 "the replicated baseline — the bit-parity contract")
        try:  # ISSUE 19 telemetry leg — log-only, never fails the phase
            t = out.get("telemetry") or {}
            drill = t.get("divergence_drill") or {}
            _log(f"phase=pretrain_zero: telemetry dp{t.get('dp')} "
                 f"stage{t.get('stage')} overhead "
                 f"{t.get('overhead_pct')}% "
                 f"(on {t.get('step_ms_on')}ms / off "
                 f"{t.get('step_ms_off')}ms, <2%="
                 f"{t.get('overhead_under_2pct')}), "
                 f"one_sync_per_step={t.get('one_sync_per_step')}, "
                 f"tok/s/chip {t.get('tokens_per_sec_per_chip')}, "
                 f"drill tripped={drill.get('tripped')} "
                 f"cond={drill.get('condition')} "
                 f"bundles={drill.get('bundle_files')}")
            if not drill.get("tripped"):
                _log("phase=pretrain_zero: WARN divergence drill did "
                     "not trip — sentinel contract")
        except Exception as e:  # noqa: BLE001 — log-only decoration
            _log(f"phase=pretrain_zero: telemetry log skipped "
                 f"({type(e).__name__}: {e})")
        try:  # ISSUE 20 bucket/overlap leg — log-only, never fails it
            b = out.get("bucketed") or {}
            cells = b.get("cells") or {}
            probes = b.get("probes") or {}
            dpk = f"dp{dp_max}"
            serial = cells.get(f"{dpk}_serial_bucket_off_fp32", {})
            overlap = cells.get(f"{dpk}_overlap_bucket_1MiB_fp32", {})
            bf16 = cells.get(f"{dpk}_overlap_bucket_1MiB_bf16", {})
            probe = probes.get(dpk, {})
            _log(f"phase=pretrain_zero: bucketed {dpk} serial "
                 f"{serial.get('tok_s')} tok/s vs overlap(1MiB) "
                 f"{overlap.get('tok_s')} (bf16 {bf16.get('tok_s')}), "
                 f"overlap_fraction={probe.get('overlap_fraction')}, "
                 f"comm_us={probe.get('comm_us')}, "
                 f"fp32_parity={b.get('parity_ok_fp32')}, "
                 f"bf16_bounded={b.get('bf16_bounded_ok')}")
        except Exception as e:  # noqa: BLE001 — log-only decoration
            _log(f"phase=pretrain_zero: bucket log skipped "
                 f"({type(e).__name__}: {e})")
        return out
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        _log(f"phase=pretrain_zero: FAIL {type(e).__name__}: {e}")
        return {"error": f"{type(e).__name__}: {str(e)[:300]}"}


def _probe_backend_init(timeout_s: float) -> str | None:
    """Backend-init watchdog: probe `jax.devices()` in a THROWAWAY
    subprocess before the child commits its own (unkillable-from-inside)
    backend init. A wedged TPU runtime — chip held by a dead process,
    libtpu lockfile, metadata-server stall — hangs exactly here, so a
    probe timeout means: force CPU now and record why, instead of eating
    the whole watchdog budget. Returns None when healthy, else a short
    reason string for the bench detail.

    BENCH_BACKEND_PROBE_CMD overrides the probed `-c` code — the test
    seam tests/test_bench_supervisor.py uses to fake a wedging backend
    without owning one."""
    code = os.environ.get("BENCH_BACKEND_PROBE_CMD",
                          "import jax; jax.devices()")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s)
        if proc.returncode != 0:
            tail = (proc.stderr or proc.stdout or "").strip()[-300:]
            return f"probe exit {proc.returncode}: {tail}"
        return None
    except subprocess.TimeoutExpired:
        return f"probe timed out after {timeout_s:.0f}s"
    except Exception as e:  # noqa: BLE001 — bench must degrade, not die
        return f"probe error {type(e).__name__}: {str(e)[:200]}"


def _read_probe_verdict() -> str | None:
    """The sticky verdict a prior attempt left (reason string), else
    None. Unreadable/garbled files read as no-verdict — the probe will
    simply run again."""
    try:
        with open(VERDICT_PATH) as f:
            v = json.load(f)
        return str(v.get("reason", "backend probe failed"))
    except (OSError, json.JSONDecodeError, ValueError):
        return None


def _write_probe_verdict(reason: str) -> None:
    """Persist a failed backend-init probe so every later attempt of
    THIS run starts pinned to CPU (best-effort — bench must degrade,
    not die)."""
    try:
        os.makedirs(TRACE_DIR, exist_ok=True)
        with open(VERDICT_PATH, "w") as f:
            json.dump({"reason": reason, "schema": "bench.probe_verdict/v1"},
                      f)
    except OSError:
        pass


def make_train_step(model, opt):
    """The bench train step (fwd + MLM loss + grad + Adam, bf16 autocast).

    Shared with tests/test_hlo_perf.py, which lowers this exact step for the
    TPU target and asserts on its HLO structure (flash custom-call present,
    bf16 matmuls, donation) — the chip-independent perf gate.
    """
    import jax

    import paddle_tpu as paddle
    from paddle_tpu import amp
    from paddle_tpu.core import tape as tape_mod
    from paddle_tpu.jit.functional import call_functional

    fused_loss = bool(getattr(getattr(model, "config", None),
                              "fused_mlm_loss", False))

    def train_step(params, buffers, opt_state, lr, t, key, ids, labels):
        def loss_of(p):
            # fused: forward returns the MLM loss directly via the chunked
            # fused_linear_cross_entropy head — no (b*s, vocab) logits
            args = ((ids, None, None, None, labels) if fused_loss
                    else (ids,))
            with amp.auto_cast(level="O1", dtype="bfloat16"):
                (out, nsp), new_buffers = call_functional(
                    model, p, buffers, args, rng_key=key, training=True)
            if fused_loss:
                return out, new_buffers
            with tape_mod.no_grad():
                loss = model.loss(paddle.Tensor(out), paddle.Tensor(nsp),
                                  paddle.Tensor(labels))
            return loss._data, new_buffers

        (loss, new_buffers), grads = jax.value_and_grad(
            loss_of, has_aux=True)(params)
        new_params, new_opt = opt.functional_step(params, grads, opt_state,
                                                  lr, t)
        return loss, new_params, new_buffers, new_opt

    return train_step


def _run_aot_gates() -> dict:
    """No chip reachable: compile the at-risk kernels through the REAL v5e
    compiler (Mosaic included) via jax.experimental.topologies — needs only
    the installed libtpu, not hardware. A pass here verifies Mosaic
    lowering+compilation, which is most of what the on-chip gates check
    (everything except actually executing); see tests/test_hlo_perf.py's
    AOT tier for the full-step and ZeRO-2 versions."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import pallas_kernels as pk

    gates: dict[str, str] = {"mode": "aot-compile (no chip; real v5e "
                             "compiler via libtpu topology)"}
    # without these, libtpu burns minutes querying the (absent) GCP
    # metadata server — 30 curl retries per variable — before topologies
    # works; safe here because this path only runs with no chip attached
    for k, v in (("TPU_SKIP_MDS_QUERY", "true"),
                 ("TPU_ACCELERATOR_TYPE", "v5litepod-4"),
                 ("TPU_WORKER_ID", "0"),
                 ("TPU_WORKER_HOSTNAMES", "localhost")):
        os.environ.setdefault(k, v)

    def topo_devices():
        from jax.experimental import topologies
        topo = topologies.get_topology_desc(platform="tpu",
                                            topology_name="v5e:2x2")
        return topo.devices

    try:
        try:
            devs = topo_devices()
        except Exception as first:  # noqa: BLE001
            # a dead process can leave the libtpu lockfile behind; clear
            # it once and retry (the error message itself says to)
            if "lockfile" in str(first):
                try:
                    os.remove("/tmp/libtpu_lockfile")
                except OSError:
                    pass
                devs = topo_devices()
            else:
                raise
        sh = jax.sharding.SingleDeviceSharding(devs[0])
    except Exception as e:  # noqa: BLE001
        gates["mode"] = f"aot unavailable: {type(e).__name__}: {str(e)[:200]}"
        return gates

    orig = pk._on_tpu
    pk._on_tpu = lambda: True

    def abs_(shape, dtype):
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sh)

    q = abs_((1, 256, 4, 64), jnp.bfloat16)
    seed = abs_((1,), jnp.int32)

    def gate(name, fn, *args):
        t0 = time.perf_counter()
        try:
            jax.jit(fn).lower(*args).compile()
            gates[name] = f"aot-ok ({time.perf_counter() - t0:.1f}s)"
        except Exception as e:  # noqa: BLE001 — gate must record, not die
            gates[name] = f"FAIL {type(e).__name__}: {str(e)[:300]}"
        _log(f"phase=gates(aot): {name}: {gates[name][:80]}")

    gate("flash_fwd",
         lambda a: pk._flash_attention_data(a, a, a, is_causal=True), q)
    gate("flash_bwd",
         lambda a: jax.grad(lambda b: pk._flash_attention_data(
             b, b, b, is_causal=True).astype(jnp.float32).sum())(a), q)
    gate("flash_dropout",
         lambda a, s: pk._flash_attention_data(a, a, a, seed=s,
                                               is_causal=True,
                                               dropout_p=0.1), q, seed)
    x = abs_((512, 1024), jnp.bfloat16)
    w = abs_((1024,), jnp.bfloat16)
    gate("fused_norms",
         lambda x_, w_: (pk.rms_norm_fused(x_, w_),
                         pk.layer_norm_fused(x_, w_, w_)), x, w)

    def ring_step(qp, mask, sd):
        kw = dict(scale=0.125, sk=256, is_causal=True, has_mask=False,
                  mask_b_is_one=True, mask_h_is_one=True,
                  mask_q_is_one=True, block_q=128, block_k=128,
                  dropout_p=0.0, interpret=False)
        return pk._fwd_call(qp, qp, qp, mask, sd,
                            offs=jnp.asarray([0, 4096], jnp.int32),
                            keep_neg_inf_lse=True, **kw)

    gate("ring_step", ring_step, abs_((1, 4, 256, 128), jnp.bfloat16),
         abs_((1, 1, 1, 1), jnp.float32), seed)

    from paddle_tpu.serving import attention as satt

    gate("paged_decode",
         lambda qq, kp, pt, pos: satt._paged_decode_pallas(qq, kp, kp, pt,
                                                           pos),
         abs_((4, 1, 8, 128), jnp.bfloat16),
         abs_((4, 16, 16, 128), jnp.bfloat16),
         abs_((4, 4), jnp.int32), abs_((4,), jnp.int32))

    gate("ragged_paged",
         lambda qq, kp, pt, pos, rid: satt._ragged_paged_pallas(
             qq, kp, kp, pt, pos, rid),
         abs_((1, 16, 8, 128), jnp.bfloat16),
         abs_((4, 16, 16, 128), jnp.bfloat16),
         abs_((4, 4), jnp.int32), abs_((16,), jnp.int32),
         abs_((16,), jnp.int32))

    # dequantizing twins: int8 pools + fp32 scale slabs at page_size 32
    # (the int8 min-tile floor _quant_kernel_ok enforces on real Mosaic)
    gate("paged_decode_quant",
         lambda qq, kp, ks, pt, pos: satt._paged_decode_pallas(
             qq, kp, kp, pt, pos, k_scale=ks, v_scale=ks),
         abs_((4, 1, 8, 128), jnp.bfloat16),
         abs_((4, 16, 32, 128), jnp.int8),
         abs_((4, 16, 32, 1), jnp.float32),
         abs_((4, 2), jnp.int32), abs_((4,), jnp.int32))

    gate("ragged_paged_quant",
         lambda qq, kp, ks, pt, pos, rid: satt._ragged_paged_pallas(
             qq, kp, kp, pt, pos, rid, k_scale=ks, v_scale=ks),
         abs_((1, 16, 8, 128), jnp.bfloat16),
         abs_((4, 16, 32, 128), jnp.int8),
         abs_((4, 16, 32, 1), jnp.float32),
         abs_((4, 2), jnp.int32), abs_((16,), jnp.int32),
         abs_((16,), jnp.int32))

    # the overlap engine's split-collective ring (ISSUE 18) over the
    # full 2x2 topology mesh: the probe body IS the ring schedule the
    # overlapped decode executables trace, so a compile here pins
    # Mosaic lowering of interleaved ppermute transports + matmuls
    t0 = time.perf_counter()
    try:
        from paddle_tpu.parallel.mesh import build_mesh
        from paddle_tpu.serving.overlap import overlap_probe_fn

        mesh = build_mesh((("tp", 4),), devices=devs)
        rep = jax.sharding.NamedSharding(mesh,
                                         jax.sharding.PartitionSpec())
        jax.jit(overlap_probe_fn(mesh, 256, 2)).lower(
            jax.ShapeDtypeStruct((8, 256), jnp.float32,
                                 sharding=rep)).compile()
        gates["paged_decode_overlap"] = (
            f"aot-ok ({time.perf_counter() - t0:.1f}s)")
    except Exception as e:  # noqa: BLE001 — gate must record, not die
        gates["paged_decode_overlap"] = (
            f"FAIL {type(e).__name__}: {str(e)[:300]}")
    _log(f"phase=gates(aot): paged_decode_overlap: "
         f"{gates['paged_decode_overlap'][:80]}")

    pk._on_tpu = orig
    return gates


def bench_child() -> None:
    # budget: 3 big compiles (batch 32 / 64 / 64r with the fused-CE scan
    # head, ~4-6 min each through the relay) + measurement; the per-phase
    # bench_partial.json still rescues a mid-run wedge
    _start_watchdog(float(os.environ.get("BENCH_WATCHDOG_SECS", "1250")))
    # static-analysis snapshot first: pure AST, no backend, ~1s — a lint
    # regression is visible even if every later phase wedges
    _enter_phase("lint", 150.0)
    lint = _run_lint()
    _enter_phase("init")
    _log("phase=init: importing jax")
    import jax

    backend_init_timeout = None
    if os.environ.get("BENCH_FORCE_CPU") == "1":
        # the axon sitecustomize pins jax_platforms at interpreter start;
        # env vars alone cannot undo it — config.update before backend init
        jax.config.update("jax_platforms", "cpu")
    elif (sticky := _read_probe_verdict()) is not None:
        # a prior attempt this run already found the backend wedged —
        # the verdict is sticky, so don't re-probe (let alone re-init)
        # the same dead runtime: start pinned to CPU immediately
        backend_init_timeout = f"sticky: {sticky}"
        _log(f"phase=init: sticky backend verdict from a prior attempt "
             f"({sticky}) — forcing CPU without re-probing")
        jax.config.update("jax_platforms", "cpu")
    else:
        # fail-fast probe: a wedged accelerator runtime hangs in
        # jax.devices() with no exception to catch — detect it in a
        # killable subprocess and fall back to CPU with the reason
        # recorded, rather than burning the child's whole watchdog budget
        backend_init_timeout = _probe_backend_init(
            float(os.environ.get("BENCH_BACKEND_PROBE_SECS", "180")))
        if backend_init_timeout is not None:
            _log(f"phase=init: backend probe failed "
                 f"({backend_init_timeout}) — forcing CPU")
            _write_probe_verdict(backend_init_timeout)
            jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu import amp
    from paddle_tpu.core import tape as tape_mod
    from paddle_tpu.core.rng import default_generator
    from paddle_tpu.jit.functional import call_functional, extract_state
    from paddle_tpu.models import ErnieConfig, ErnieForPretraining

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    _log(f"phase=init: backend up, device={getattr(dev, 'device_kind', dev.platform)}")

    # tiny compile first: verifies the backend can compile+run at all before
    # we sink 20-40s into the big StableHLO program
    _enter_phase("smoke", 300.0)
    x = jnp.ones((128, 128), jnp.bfloat16)
    y = jax.jit(lambda a: (a @ a).sum())(x)
    float(np.asarray(y))
    _log("phase=smoke: tiny matmul compiled and ran")

    # Pallas lowering gates next: cheap compiles, maximal hardware signal
    _enter_phase("gates")
    gates = _run_gates(on_tpu)

    # serving prefix-cache phase: tiny model, bounded budget, non-fatal
    _enter_phase("serving_prefix", 400.0)
    serving_prefix = _run_serving_prefix(on_tpu)

    # decode-horizon serving phase: same tiny model budget, non-fatal
    _enter_phase("serving_decode", 400.0)
    serving_decode = _run_serving_decode(on_tpu)

    # tensor-parallel sweep: parity bit + psum probe, null tok/s on CPU
    _enter_phase("serving_tp", 400.0)
    serving_tp = _run_serving_tp(on_tpu)

    # collective/compute overlap: serial vs ring-chunked psum, parity
    # bit + overlap fraction (~0 on CPU is the expected null)
    _enter_phase("serving_tp_overlap", 400.0)
    serving_tp_overlap = _run_serving_tp_overlap(on_tpu)

    # speculative-decoding phase: accept rate + tokens/target-step,
    # greedy parity; tok/s null on CPU by design
    _enter_phase("serving_spec", 400.0)
    serving_spec = _run_serving_spec(on_tpu)

    # seeded chaos phase: fault-injected run vs fault-free parity
    _enter_phase("serving_faults", 400.0)
    serving_faults = _run_serving_faults(on_tpu)

    # chunked-prefill interference phase: stall-free batching on vs off
    _enter_phase("serving_chunked", 400.0)
    serving_chunked = _run_serving_chunked(on_tpu)

    # ragged mixed-step phase: one flat executable per step vs chained
    _enter_phase("serving_ragged", 400.0)
    serving_ragged = _run_serving_ragged(on_tpu)

    # crash-recovery phase: supervisor kill/rebuild/re-admit parity
    _enter_phase("serving_recovery", 400.0)
    serving_recovery = _run_serving_recovery(on_tpu)

    # replicated-cluster phase: replica kill, migration, affinity payoff
    _enter_phase("serving_cluster", 400.0)
    serving_cluster = _run_serving_cluster(on_tpu)

    # observability v2 phase: SLO goodput, recorder cost, death bundle
    _enter_phase("serving_slo", 400.0)
    serving_slo = _run_serving_slo(on_tpu)

    # quantized-serving phase: int8 capacity/parity + qar psum probe
    _enter_phase("serving_quant", 400.0)
    serving_quant = _run_serving_quant(on_tpu)

    # ZeRO pretrain phase: replicated vs sharded dp, 1/dp bytes + parity
    _enter_phase("pretrain_zero", 400.0)
    pretrain_zero = _run_pretrain_zero(on_tpu)
    _enter_phase("build")

    if on_tpu:
        cfg = ErnieConfig.ernie_base()  # ERNIE-1.0: L12 H768 A12 vocab 18k
        cfg.fused_mlm_loss = True       # chunked CE head (PERF_NOTES r5)
        # dropout masks from the hardware PRNG instead of threefry's 20 u32
        # rounds per element (PERF_NOTES r5 trace); opt-out by pre-setting
        # the var to ""
        os.environ.setdefault("PADDLE_TPU_RNG_IMPL", "rbg")
        batch, seq, steps, warmup = 32, 512, 20, 3
        # BENCH_REMAT=1: checkpoint encoder layers — AOT memory analysis
        # (PERF_NOTES r5) shows batch 64+ needs it to fit 16 GB
        if os.environ.get("BENCH_REMAT") == "1":
            cfg.recompute = True
    else:  # CPU smoke fallback; driver runs on TPU
        cfg = ErnieConfig.tiny()
        batch, seq, steps, warmup = 8, 128, 5, 1
    # sweep hooks (used by the perf-tuning harness; driver runs defaults)
    batch = int(os.environ.get("BENCH_BATCH", batch))
    seq = int(os.environ.get("BENCH_SEQ", seq))
    steps = int(os.environ.get("BENCH_STEPS", steps))

    model = ErnieForPretraining(cfg)
    model.train()
    opt = paddle.optimizer.Adam(learning_rate=1e-4,
                                parameters=model.parameters())

    params, buffers = extract_state(model)
    opt_state = opt.functional_state(params)
    # host-side snapshot BEFORE any jitted call: the jitted step donates
    # params/buffers/opt_state, so after the first call (or a failed sweep
    # step) the live arrays are deleted on TPU; recovery must restore from
    # this copy, never re-extract from the model (advisor r3 finding).
    # Only the sweep's OOM path consumes it, so only take the ~1GB
    # device->host copy when the sweep will actually run.
    # sweep entries: "64" = plain, "64r" = with activation checkpointing
    # (remat). With the fused CE head the plain batch-64 step fits a v5e
    # (AOT memory analysis: 15.74 GB of 16 — PERF_NOTES r5); the OOM
    # recovery below stays armed for the 0.26 GB of headroom. Remat legs
    # remain as fallbacks (measured slower: recompute > batch efficiency).
    try:
        sweep_batches = []
        # 128r dropped from the default: measured 66.4k tok/s vs 66.9k
        # (64r) and 84.8k (32) in r5 — not worth a 4th big compile
        for tok in os.environ.get("BENCH_SWEEP", "64,64r").split(","):
            tok = tok.strip()
            if not tok:
                continue
            use_r = tok.endswith("r")
            sweep_batches.append((int(tok[:-1] if use_r else tok), use_r))
    except ValueError:  # malformed override: skip the sweep, don't crash
        _log("phase=build: malformed BENCH_SWEEP ignored")
        sweep_batches = []
    will_sweep = (on_tpu and "BENCH_BATCH" not in os.environ
                  and bool(sweep_batches))
    snapshot = jax.tree_util.tree_map(
        lambda a: np.asarray(a),
        (params, buffers, opt_state)) if will_sweep else None

    def restore_state():
        return jax.tree_util.tree_map(jnp.asarray, snapshot)

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    labels = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)))
    _log(f"phase=build: model built, batch={batch} seq={seq}")

    jitted = jax.jit(make_train_step(model, opt), donate_argnums=(0, 1, 2))
    lr = jnp.float32(1e-4)
    step_no = [0]
    _remat_step = [None]

    def remat_step():
        """Lazily-jitted step over the SAME weights with encoder-layer
        checkpointing (the 'r' sweep entries / final phase)."""
        if _remat_step[0] is None:
            import dataclasses

            cfg_r = dataclasses.replace(cfg, recompute=True)
            model_r = ErnieForPretraining(cfg_r)
            model_r.train()
            _remat_step[0] = jax.jit(make_train_step(model_r, opt),
                                     donate_argnums=(0, 1, 2))
        return _remat_step[0]

    def run_steps(n, ids, labels, sync_each=False, step_fn=None):
        nonlocal params, buffers, opt_state
        fn = step_fn or jitted
        loss = None
        t0 = time.perf_counter()
        for _ in range(n):
            step_no[0] += 1
            key = default_generator().next_key()
            loss, params, buffers, opt_state = fn(
                params, buffers, opt_state, lr, jnp.int32(step_no[0]), key,
                ids, labels)
            if sync_each:
                float(np.asarray(loss))
        # sync via a device->host value fetch: the final loss depends on
        # every queued step, and on some PJRT transports (axon relay)
        # block_until_ready returns before queued work drains
        final = float(np.asarray(loss))
        return time.perf_counter() - t0, final

    def data_for(b):
        return (jnp.asarray(rng.randint(0, cfg.vocab_size, (b, seq))),
                jnp.asarray(rng.randint(0, cfg.vocab_size, (b, seq))))

    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    # PaLM-style: 6N per token (fwd+bwd) + attention 12*L*H*seq
    flops_per_token = 6 * n_params + 12 * cfg.num_hidden_layers * \
        cfg.hidden_size * seq
    peak = _peak_flops(dev)

    def result_json(tps, b, n_steps, dt, loss, phase):
        mfu = (tps * flops_per_token / peak) if peak else 0.0
        return {
            "metric": METRIC,
            "value": round(tps, 1),
            "unit": UNIT,
            "vs_baseline": round(mfu / 0.40, 4),
            "detail": {
                "device": getattr(dev, "device_kind", dev.platform),
                "batch": b, "seq": seq, "steps": n_steps,
                "step_time_ms": round(dt / n_steps * 1e3, 2),
                "mfu": round(mfu, 4),
                "params": n_params,
                "final_loss": loss,
                "phase": phase,
                "gates": gates,
                "serving_prefix": serving_prefix,
                "serving_decode": serving_decode,
                "serving_tp": serving_tp,
                "serving_tp_overlap": serving_tp_overlap,
                "serving_spec": serving_spec,
                "serving_faults": serving_faults,
                "serving_chunked": serving_chunked,
                "serving_ragged": serving_ragged,
                "serving_recovery": serving_recovery,
                "serving_cluster": serving_cluster,
                "serving_slo": serving_slo,
                "serving_quant": serving_quant,
                "pretrain_zero": pretrain_zero,
                "backend_init_timeout": backend_init_timeout,
                "lint": lint,
                "observability": _obs_snapshot(),
            },
        }

    # --- phase: quick MFU at the round-2 reference config -----------------
    _enter_phase("quick")
    run_steps(2, ids, labels, sync_each=True)  # compile + warm
    dt_q, loss_q = run_steps(5, ids, labels)
    tps_q = batch * seq * 5 / dt_q
    best = result_json(tps_q, batch, 5, dt_q, loss_q, "quick")
    _write_partial(best)
    _log(f"phase=quick: batch={batch} -> {tps_q:,.0f} tok/s "
         f"(mfu={best['detail']['mfu']:.3f})")

    # --- phase: batch micro-sweep (TPU only, no explicit override) --------
    _enter_phase("sweep", 1000.0)
    sweep_detail = {str(batch): round(tps_q, 1)}
    best_r = False
    if will_sweep:
        best_b, best_tps = batch, tps_q
        for b, use_r in sweep_batches:
            tag = f"{b}{'r' if use_r else ''}"
            try:
                sf = remat_step() if use_r else jitted
                bi, bl = data_for(b)
                run_steps(2, bi, bl, sync_each=True,
                          step_fn=sf)                     # compile + warm
                dt_s, _ = run_steps(5, bi, bl, step_fn=sf)
                tps = b * seq * 5 / dt_s
                sweep_detail[tag] = round(tps, 1)
                _log(f"phase=sweep: batch={tag} -> {tps:,.0f} tok/s")
                if tps > best_tps:
                    best_b, best_tps, best_r = b, tps, use_r
            except Exception as e:  # OOM etc.: try the NEXT entry (a later
                # remat entry may fit where a plain one OOMed)
                _log(f"phase=sweep: batch={tag} failed ({type(e).__name__})")
                # the failed jitted call donated/poisoned the state arrays;
                # restore from the host snapshot (NOT extract_state — those
                # buffers were donated and deleted)
                params, buffers, opt_state = restore_state()
        batch = best_b
        _log(f"phase=sweep: picked batch={batch}"
             + (" (remat)" if best_r else ""))
        ids, labels = data_for(batch)

    # --- phase: final measurement with profiler trace ---------------------
    _enter_phase("final")
    final_step = remat_step() if best_r else jitted
    run_steps(warmup, ids, labels, sync_each=True, step_fn=final_step)
    _log(f"phase=warmup: {warmup} steps done (batch={batch})")
    trace_ok = False
    if on_tpu and os.environ.get("BENCH_TRACE", "1") == "1":
        try:
            jax.profiler.start_trace(TRACE_DIR)
            trace_ok = True
        except Exception as e:  # noqa: BLE001
            _log(f"phase=trace: start failed ({type(e).__name__}: {e})")
    dt, final_loss = run_steps(steps, ids, labels,
                               step_fn=final_step)
    if trace_ok:
        try:
            jax.profiler.stop_trace()
            _log(f"phase=trace: saved to {TRACE_DIR}")
        except Exception:  # noqa: BLE001
            pass
    _log(f"phase=measure: {steps} steps in {dt:.2f}s")

    tokens_per_sec = batch * seq * steps / dt
    final = result_json(tokens_per_sec, batch, steps, dt, final_loss, "final")
    final["detail"]["sweep"] = {str(k): v for k, v in sweep_detail.items()}
    final["detail"]["remat"] = best_r
    _write_partial(final)
    _emit(final)


# --------------------------------------------------------------------------
# supervisor: fresh child per attempt, CPU fallback, guaranteed JSON
# --------------------------------------------------------------------------

def _run_child(extra_env: dict, timeout: float) -> str | None:
    """Run one child attempt; return its JSON line on success else None."""
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    env.update(extra_env)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            stdout=subprocess.PIPE, stderr=sys.stderr,
            text=True, timeout=timeout, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        _log(f"attempt timed out after {timeout}s")
        return None
    last_err = None
    for line in reversed(proc.stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except json.JSONDecodeError:
                continue
            if parsed.get("metric") == METRIC and "error" not in parsed:
                return line
            if last_err is None and parsed.get("error"):
                last_err = parsed["error"]
    # the wedged phase name (per-phase watchdog) surfaces in the tail here
    _log(f"attempt failed rc={proc.returncode}"
         + (f": {last_err[:300]}" if last_err else ""))
    return None


def _backend_wedged_verdict() -> str | None:
    """Did the previous attempt die inside backend init? Either the
    child's probe caught it (sticky verdict file) or the child hard-
    wedged before/inside init and the per-phase watchdog recorded
    wedged_phase=init|smoke in the partial. Returns the reason string,
    else None (attempt died later — the backend itself came up, retry
    it)."""
    reason = _read_probe_verdict()
    if reason is not None:
        return reason
    try:
        with open(PARTIAL_PATH) as f:
            detail = json.load(f).get("detail", {})
    except (OSError, json.JSONDecodeError):
        return None
    wedged = detail.get("wedged_phase")
    if wedged in ("init", "smoke"):
        return f"prior attempt wedged in phase={wedged}"
    return None


def _read_partial() -> dict | None:
    """A TPU partial result left by a wedged child beats a CPU fallback."""
    try:
        with open(PARTIAL_PATH) as f:
            parsed = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if parsed.get("metric") != METRIC or parsed.get("value", 0) <= 0:
        return None
    if parsed.get("detail", {}).get("device", "cpu") == "cpu":
        return None
    return parsed


def main() -> None:
    if os.environ.get("BENCH_CHILD") == "1":
        try:
            bench_child()
        except BaseException as e:  # noqa: BLE001 — must emit JSON, not die
            _log(f"child failed: {type(e).__name__}: {e}")
            _emit(_error_json(f"{type(e).__name__}: {e}"))
            sys.exit(3)
        return

    # stale partials/verdicts from a previous run must not masquerade as
    # this run's
    for stale in (PARTIAL_PATH, VERDICT_PATH):
        try:
            os.remove(stale)
        except OSError:
            pass

    # supervisor: retry the default (TPU) backend twice, then CPU fallback.
    # The backend-init verdict is STICKY across attempts (BENCH_r05): once
    # attempt 1 dies inside init — probe-detected (verdict file) or hard-
    # wedged (partial's wedged_phase) — every later attempt starts pinned
    # to CPU instead of re-importing jax on the same dead runtime and
    # burning its whole budget with no parsed metric.
    timeouts = [1350.0, 700.0]
    cpu_reason = None
    for i, timeout in enumerate(timeouts):
        if cpu_reason is None and i > 0:
            cpu_reason = _backend_wedged_verdict()
        extra_env = {}
        if cpu_reason is not None:
            extra_env["BENCH_FORCE_CPU"] = "1"
            _log(f"supervisor: attempt {i + 1} pinned to CPU "
                 f"(sticky backend verdict: {cpu_reason})")
        _log(f"supervisor: attempt {i + 1}/{len(timeouts)} (timeout {timeout}s)")
        line = _run_child(extra_env, timeout)
        if line is not None:
            if cpu_reason is not None:
                # a pinned-CPU attempt can never be a TPU number: mark it
                # exactly like the terminal CPU fallback would
                parsed = json.loads(line)
                parsed["error"] = \
                    "tpu backend unavailable; CPU fallback number"
                parsed["vs_baseline"] = 0.0
                parsed.setdefault("detail", {})["backend_verdict"] = \
                    cpu_reason
                _emit(parsed)
                return
            print(line, flush=True)
            return
        if i + 1 < len(timeouts):
            time.sleep(10)  # backoff: give a flaky backend time to recover

    # both TPU attempts failed: a partial TPU number from a wedged child
    # still beats the CPU fallback below
    partial = _read_partial()
    if partial is not None:
        _log("supervisor: children died but left a TPU partial — emitting it")
        partial.setdefault("detail", {})["note"] = \
            "partial: child wedged mid-run; value is last completed phase"
        _emit(partial)
        return

    _log("supervisor: TPU attempts exhausted, falling back to CPU")
    line = _run_child({"BENCH_FORCE_CPU": "1"}, 600.0)
    if line is not None:
        parsed = json.loads(line)
        parsed["error"] = "tpu backend unavailable; CPU fallback number"
        parsed["vs_baseline"] = 0.0
        _emit(parsed)
        return

    err = _error_json("all attempts failed (tpu x2, cpu x1)")
    try:  # even a value-less partial names the phases reached before wedging
        with open(PARTIAL_PATH) as f:
            detail = json.load(f).get("detail", {})
        err["detail"] = {k: detail[k] for k in
                         ("phases_completed", "wedged_phase") if k in detail}
    except (OSError, json.JSONDecodeError):
        pass
    _emit(err)


if __name__ == "__main__":
    main()
