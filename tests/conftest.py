"""Test harness config.

Mirrors the reference's single-host multi-device emulation (SURVEY.md §4):
8 fake devices on CPU via xla_force_host_platform_device_count so every
mesh/collective/parallelism test runs hermetically without TPU hardware.
Must run before jax is first imported.
"""
import os

# PADDLE_TPU_TEST_PLATFORM=tpu runs the suite on real hardware instead of the
# hermetic 8-fake-device CPU default.
_plat = os.environ.get("PADDLE_TPU_TEST_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if _plat == "cpu" and "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402
import jax  # noqa: E402

# The axon sitecustomize pins jax_platforms to "axon,cpu" at interpreter
# start; env vars alone cannot undo that, so select the backend via config
# before any backend is initialized.
if _plat != "axon":
    jax.config.update("jax_platforms", _plat)

# full fp32 matmuls for numeric comparisons (TPU bench keeps its own default)
jax.config.update("jax_default_matmul_precision", "highest")


def pytest_configure(config):
    # the tier-1 fast lane runs `-m 'not slow'`; anything that compiles
    # beyond a module's core executable set carries this marker
    config.addinivalue_line(
        "markers", "slow: heavy test excluded from the tier-1 fast lane")


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(autouse=True)
def _seed_framework():
    import paddle_tpu as paddle

    paddle.seed(1234)
    yield
