"""distribution / sparse / quantization / text / audio / device / utils /
profiler — the aux subpackages filled in round 2 (verdict items #4, #9)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


# ------------------------------------------------------------- distribution
class TestDistribution:
    def test_normal_moments_and_logprob(self):
        from paddle_tpu.distribution import Normal

        d = Normal(loc=1.0, scale=2.0)
        s = d.sample((20000,))
        assert abs(float(s.numpy().mean()) - 1.0) < 0.1
        assert abs(float(s.numpy().std()) - 2.0) < 0.1
        # log_prob matches the closed form at the mean
        lp = float(d.log_prob(paddle.to_tensor(1.0)).numpy())
        np.testing.assert_allclose(lp, -np.log(2.0 * np.sqrt(2 * np.pi)),
                                   rtol=1e-5)

    def test_normal_rsample_differentiable(self):
        from paddle_tpu.distribution import Normal

        loc = paddle.to_tensor(np.float32(0.5), stop_gradient=False)
        d = Normal(loc=loc, scale=1.0)
        # rsample flows gradient to loc through the reparameterization
        out = d.rsample((16,))
        assert out.numpy().shape == (16,)

    def test_kl_normal(self):
        from paddle_tpu.distribution import Normal, kl_divergence

        p = Normal(0.0, 1.0)
        q = Normal(1.0, 2.0)
        kl = float(kl_divergence(p, q).numpy())
        expect = np.log(2.0) + (1 + 1) / (2 * 4) - 0.5
        np.testing.assert_allclose(kl, expect, rtol=1e-5)
        assert float(kl_divergence(p, p).numpy()) == pytest.approx(0.0)

    def test_categorical(self):
        from paddle_tpu.distribution import Categorical

        probs = np.array([0.1, 0.2, 0.7], dtype="float32")
        d = Categorical(probs=probs)
        s = d.sample((5000,))
        freq = np.bincount(np.asarray(s.numpy()).astype(int),
                           minlength=3) / 5000
        np.testing.assert_allclose(freq, probs, atol=0.05)
        ent = float(d.entropy().numpy())
        np.testing.assert_allclose(ent, -(probs * np.log(probs)).sum(),
                                   rtol=1e-4)

    def test_bernoulli_gamma_beta(self):
        from paddle_tpu.distribution import Bernoulli, Beta, Gamma

        b = Bernoulli(probs=0.3)
        np.testing.assert_allclose(float(b.mean.numpy()), 0.3, rtol=1e-6)
        g = Gamma(concentration=2.0, rate=0.5)
        np.testing.assert_allclose(float(g.mean.numpy()), 4.0, rtol=1e-6)
        s = g.sample((8000,))
        assert abs(float(s.numpy().mean()) - 4.0) < 0.3
        be = Beta(2.0, 3.0)
        np.testing.assert_allclose(float(be.mean.numpy()), 0.4, rtol=1e-6)

    def test_transformed_lognormal_consistency(self):
        from paddle_tpu.distribution import (
            ExpTransform, LogNormal, Normal, TransformedDistribution,
        )

        base = Normal(0.0, 0.5)
        td = TransformedDistribution(base, [ExpTransform()])
        ln = LogNormal(0.0, 0.5)
        for v in (0.5, 1.0, 2.3):
            np.testing.assert_allclose(
                float(td.log_prob(paddle.to_tensor(v)).numpy()),
                float(ln.log_prob(paddle.to_tensor(v)).numpy()), rtol=1e-5)

    def test_independent_sums_event_dims(self):
        from paddle_tpu.distribution import Independent, Normal

        d = Independent(Normal(np.zeros(3, "float32"),
                               np.ones(3, "float32")), 1)
        lp = d.log_prob(paddle.to_tensor(np.zeros(3, "float32")))
        np.testing.assert_allclose(
            float(lp.numpy()), 3 * -0.5 * np.log(2 * np.pi), rtol=1e-5)


# ------------------------------------------------------------------- sparse
class TestSparse:
    def test_coo_roundtrip(self):
        from paddle_tpu import sparse

        dense = np.array([[0, 1, 0], [2, 0, 3]], dtype="float32")
        idx = np.array([[0, 1, 1], [1, 0, 2]])
        vals = np.array([1, 2, 3], dtype="float32")
        t = sparse.sparse_coo_tensor(idx, vals, [2, 3])
        np.testing.assert_array_equal(np.asarray(t.to_dense().numpy()),
                                      dense)
        assert t.nnz == 3

    def test_coo_csr_conversion(self):
        from paddle_tpu import sparse

        idx = np.array([[0, 1, 1], [1, 0, 2]])
        vals = np.array([1, 2, 3], dtype="float32")
        coo = sparse.sparse_coo_tensor(idx, vals, [2, 3])
        csr = coo.to_sparse_csr()
        np.testing.assert_array_equal(np.asarray(csr.crows().numpy()),
                                      [0, 1, 3])
        back = csr.to_sparse_coo()
        np.testing.assert_array_equal(np.asarray(back.to_dense().numpy()),
                                      np.asarray(coo.to_dense().numpy()))

    def test_spmm_matches_dense(self):
        from paddle_tpu import sparse

        rng = np.random.RandomState(0)
        dense_a = (rng.rand(8, 6) * (rng.rand(8, 6) > 0.7)).astype("float32")
        b = rng.randn(6, 5).astype("float32")
        idx = np.stack(np.nonzero(dense_a))
        coo = sparse.sparse_coo_tensor(idx, dense_a[tuple(idx)], [8, 6])
        out = sparse.matmul(coo, b)
        np.testing.assert_allclose(np.asarray(out.numpy()), dense_a @ b,
                                   rtol=1e-5, atol=1e-5)
        csr = coo.to_sparse_csr()
        out2 = sparse.matmul(csr, b)
        np.testing.assert_allclose(np.asarray(out2.numpy()), dense_a @ b,
                                   rtol=1e-5, atol=1e-5)

    def test_coalesce_and_unary(self):
        from paddle_tpu import sparse

        idx = np.array([[0, 0, 1], [1, 1, 0]])  # duplicate (0,1)
        vals = np.array([1.0, 2.0, -4.0], dtype="float32")
        t = sparse.sparse_coo_tensor(idx, vals, [2, 2]).coalesce()
        assert t.nnz == 2
        dense = np.asarray(t.to_dense().numpy())
        np.testing.assert_allclose(dense, [[0, 3], [-4, 0]])
        r = sparse.relu(t)
        np.testing.assert_allclose(np.asarray(r.to_dense().numpy()),
                                   [[0, 3], [0, 0]])

    def test_csr_softmax_rows(self):
        from paddle_tpu import sparse

        crows = [0, 2, 3]
        cols = [0, 2, 1]
        vals = np.array([1.0, 1.0, 5.0], dtype="float32")
        csr = sparse.sparse_csr_tensor(crows, cols, vals, [2, 3])
        sm = sparse.nn.Softmax()(csr)
        out = np.asarray(sm.values().numpy())
        np.testing.assert_allclose(out[:2], [0.5, 0.5], rtol=1e-5)
        np.testing.assert_allclose(out[2], 1.0, rtol=1e-5)


# ------------------------------------------------------------- quantization
class TestQuantization:
    def test_qdq_grid(self):
        from paddle_tpu.quantization import quantize_dequantize

        x = paddle.to_tensor(np.array([-1.0, -0.5, 0.0, 0.3, 1.0],
                                      dtype="float32"))
        out = np.asarray(quantize_dequantize(x, 1.0, bits=8).numpy())
        # values land on the int8 grid: x*127 integral
        np.testing.assert_allclose(out * 127, np.round(out * 127),
                                   atol=1e-4)
        np.testing.assert_allclose(out, np.asarray(x.numpy()), atol=1 / 127)

    def test_observers(self):
        from paddle_tpu.quantization import AbsmaxObserver, HistObserver

        obs = AbsmaxObserver()
        obs(paddle.to_tensor(np.array([1.0, -3.0], "float32")))
        obs(paddle.to_tensor(np.array([2.0], "float32")))
        assert float(obs.scales().numpy()) == 3.0
        h = HistObserver(percent=1.0)
        h(paddle.to_tensor(np.linspace(-2, 2, 1000).astype("float32")))
        assert abs(float(h.scales().numpy()) - 2.0) < 0.01

    def test_qat_swaps_and_runs(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization import (
            FakeQuanterChannelWiseAbsMaxObserver,
            FakeQuanterWithAbsMaxObserver, QAT, QuantConfig, QuantedLinear,
        )

        model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        cfg = QuantConfig(activation=FakeQuanterWithAbsMaxObserver,
                          weight=FakeQuanterChannelWiseAbsMaxObserver)
        q = QAT(cfg).quantize(model)
        assert any(isinstance(l, QuantedLinear)
                   for l in q.sublayers(include_self=True))
        x = paddle.to_tensor(np.random.RandomState(0)
                             .randn(3, 4).astype("float32"))
        out_q = q(x)
        out_f = model(x)
        assert out_q.numpy().shape == (3, 2)
        # int8 qdq stays close to the float path
        np.testing.assert_allclose(np.asarray(out_q.numpy()),
                                   np.asarray(out_f.numpy()), atol=0.15)

    def test_ptq_calibrate_convert(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.quantization import PTQ, QuantConfig

        model = nn.Sequential(nn.Linear(4, 4))
        ptq = PTQ(QuantConfig(None, None))
        q = ptq.quantize(model)
        for _ in range(3):
            q(paddle.to_tensor(np.random.RandomState(0)
                               .randn(2, 4).astype("float32")))
        converted = ptq.convert(q)
        out = converted(paddle.to_tensor(np.ones((1, 4), "float32")))
        assert np.isfinite(np.asarray(out.numpy())).all()


# --------------------------------------------------------------------- text
class TestText:
    def test_datasets_shapes(self):
        import warnings

        from paddle_tpu.text import Imdb, UCIHousing

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            ds = Imdb(mode="train")
            doc, label = ds[0]
            assert doc.dtype == np.int64 and label in (0, 1)
            uci = UCIHousing(mode="test")
            x, y = uci[0]
            assert x.shape == (13,) and y.shape == (1,)

    def test_viterbi_matches_bruteforce(self):
        from itertools import product

        from paddle_tpu.text import viterbi_decode

        rng = np.random.RandomState(0)
        B, T, N = 2, 4, 3
        emit = rng.randn(B, T, N).astype("float32")
        trans = rng.randn(N, N).astype("float32")
        scores, paths = viterbi_decode(emit, trans,
                                       include_bos_eos_tag=False)
        for b in range(B):
            best, best_path = -1e9, None
            for path in product(range(N), repeat=T):
                s = emit[b, 0, path[0]] + sum(
                    trans[path[t - 1], path[t]] + emit[b, t, path[t]]
                    for t in range(1, T))
                if s > best:
                    best, best_path = s, path
            np.testing.assert_allclose(float(scores.numpy()[b]), best,
                                       rtol=1e-5)
            np.testing.assert_array_equal(np.asarray(paths.numpy()[b]),
                                          best_path)


# -------------------------------------------------------------------- audio
class TestAudio:
    def test_mel_fbank_shape_and_coverage(self):
        from paddle_tpu.audio import compute_fbank_matrix

        fb = np.asarray(compute_fbank_matrix(16000, 512, n_mels=40))
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        assert (fb.sum(axis=1) > 0).all()  # every filter covers some bins

    def test_spectrogram_sine_peak(self):
        import jax.numpy as jnp

        from paddle_tpu.audio import Spectrogram

        sr, f0 = 16000, 1000.0
        t = np.arange(sr) / sr
        sig = np.sin(2 * np.pi * f0 * t).astype("float32")
        spec = Spectrogram(n_fft=512, hop_length=256)(jnp.asarray(sig))
        mag = np.asarray(spec.numpy())  # [F, frames]
        peak_bin = mag.mean(axis=1).argmax()
        expect_bin = round(f0 / (sr / 512))
        assert abs(int(peak_bin) - expect_bin) <= 1

    def test_mfcc_pipeline_shapes(self):
        import jax.numpy as jnp

        from paddle_tpu.audio import MFCC

        sig = np.random.RandomState(0).randn(2, 8000).astype("float32")
        out = MFCC(sr=16000, n_mfcc=13, n_fft=512)(jnp.asarray(sig))
        arr = np.asarray(out.numpy())
        assert arr.shape[0] == 2 and arr.shape[1] == 13

    def test_wav_roundtrip(self, tmp_path):
        import warnings

        from paddle_tpu import audio

        sig = (np.sin(np.linspace(0, 100, 1600))[None]
               .astype("float32") * 0.5)
        path = str(tmp_path / "t.wav")
        audio.save(path, sig, 16000)
        loaded, sr = audio.load(path)
        assert sr == 16000
        np.testing.assert_allclose(np.asarray(loaded.numpy()), sig,
                                   atol=1e-3)
        meta = audio.info(path)
        assert meta.num_frames == 1600 and meta.num_channels == 1

    def test_hz_mel_inverse(self):
        from paddle_tpu.audio import hz_to_mel, mel_to_hz

        for hz in (100.0, 440.0, 4000.0):
            np.testing.assert_allclose(mel_to_hz(hz_to_mel(hz)), hz,
                                       rtol=1e-4)
            np.testing.assert_allclose(
                mel_to_hz(hz_to_mel(hz, htk=True), htk=True), hz, rtol=1e-4)


# ----------------------------------------------------------- device / utils
class TestDeviceUtils:
    def test_device_synchronize_and_streams(self):
        dev = paddle.device
        dev.synchronize()
        s = dev.Stream()
        import jax.numpy as jnp

        x = jnp.ones((8,)) * 2
        s.track(x)
        e = s.record_event()
        e.synchronize()
        assert s.query() in (True, False)
        with dev.stream_guard(dev.Stream()) as s2:
            assert dev.current_stream() is s2

    def test_memory_allocated_nonzero(self):
        import jax.numpy as jnp

        keep = jnp.ones((1024, 1024), jnp.float32)  # noqa: F841
        assert paddle.device.memory_allocated() > 0

    def test_dlpack_roundtrip(self):
        t = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
        cap = paddle.utils.dlpack.to_dlpack(t)
        back = paddle.utils.dlpack.from_dlpack(cap)
        np.testing.assert_array_equal(np.asarray(back.numpy()),
                                      np.asarray(t.numpy()))

    def test_run_check(self, capsys):
        paddle.utils.run_check()
        assert "works" in capsys.readouterr().out

    def test_cpp_extension_builds_and_runs(self, tmp_path):
        src = tmp_path / "myop.cc"
        src.write_text(
            '#include <cstdint>\n'
            'extern "C" void double_op(const float* in, float* out, '
            'int64_t n) { for (int64_t i = 0; i < n; ++i) out[i] = '
            '2.0f * in[i]; }\n')
        from paddle_tpu.utils import cpp_extension

        mod = cpp_extension.load(
            "double_op", [str(src)], functions=["double_op"],
            build_directory=str(tmp_path))
        x = paddle.to_tensor(np.array([1.0, 2.5], dtype="float32"))
        out = mod.double_op(x)
        np.testing.assert_allclose(np.asarray(out.numpy()), [2.0, 5.0])


# ----------------------------------------------------------------- profiler
class TestProfiler:
    def test_scheduler_states(self):
        from paddle_tpu.profiler import ProfilerState, make_scheduler

        sch = make_scheduler(closed=1, ready=1, record=2, repeat=1)
        states = [sch(i) for i in range(5)]
        assert states[0] == ProfilerState.CLOSED
        assert states[1] == ProfilerState.READY
        assert states[2] == ProfilerState.RECORD
        assert states[3] == ProfilerState.RECORD_AND_RETURN
        assert states[4] == ProfilerState.CLOSED

    def test_record_events_and_summary(self, tmp_path):
        import time

        from paddle_tpu import profiler

        traces = str(tmp_path / "traces")
        with profiler.Profiler(
                scheduler=profiler.make_scheduler(closed=0, ready=0,
                                                  record=3, repeat=1),
                on_trace_ready=profiler.export_chrome_tracing(traces),
                timer_only=True) as p:
            for _ in range(3):
                with profiler.RecordEvent("work"):
                    time.sleep(0.002)
                p.step()
        s = p.summary()
        assert "work" in s
        files = os.listdir(traces)
        assert len(files) == 1
        loaded = profiler.load_profiler_result(os.path.join(traces,
                                                            files[0]))
        names = {ev["name"] for ev in loaded["traceEvents"]}
        assert "work" in names

    def test_record_event_outside_profiler_is_noop(self):
        from paddle_tpu import profiler

        with profiler.RecordEvent("orphan"):
            pass  # must not raise or leak into any profiler


def test_tape_overhead_benchmark_smoke():
    """benchmarks/tape_overhead.py runs and yields sane numbers."""
    import importlib.util
    import os

    path = os.path.join(os.path.dirname(__file__), "..", "benchmarks",
                        "tape_overhead.py")
    spec = importlib.util.spec_from_file_location("tape_overhead", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = mod.measure(n_ops=5)
    assert out["per_op_us"]["dispatch_tape"] > 0
    assert out["train_step_ms"]["jitted_functional"] > 0


def test_check_nan_inf_flag_guards_jitted_paths():
    """FLAGS_check_nan_inf must catch NaNs in BOTH regimes: eager dispatch
    (op-output check) and jitted steps (jax_debug_nans wiring)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pytest

    import paddle_tpu as paddle

    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        assert jax.config.jax_debug_nans
        # eager: the dispatcher raises on a nan output
        bad = paddle.to_tensor(np.float32([1.0, -1.0]))
        with pytest.raises(FloatingPointError):
            bad.log()  # log(-1) = nan
        # jitted: XLA debug_nans raises out of the compiled computation
        with pytest.raises(FloatingPointError):
            jax.jit(lambda v: jnp.log(v))(jnp.float32([-1.0])).block_until_ready()
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
        assert not jax.config.jax_debug_nans


def test_custom_device_plugin_seam(tmp_path):
    """PJRT-plugin registration seam: validation + bookkeeping (a real
    vendor .so cannot be loaded hermetically; the registration path into
    jax's plugin registry is exercised up to the library check)."""
    import pytest

    from paddle_tpu.device.plugin import (
        is_custom_device_registered, list_custom_devices,
        register_custom_device,
    )

    with pytest.raises(ValueError, match="invalid"):
        register_custom_device("my-npu!", library_path="x.so")
    with pytest.raises(ValueError, match="library_path"):
        register_custom_device("mynpu")
    with pytest.raises(FileNotFoundError):
        register_custom_device("mynpu", library_path=str(tmp_path / "no.so"))
    assert not is_custom_device_registered("mynpu")
    assert list_custom_devices() == []


def test_registered_custom_device_visible_to_device_api(monkeypatch):
    """A registered plugin must be selectable + discoverable by the rest of
    the device API (set_device / is_compiled_with_custom_device /
    get_all_custom_device_type)."""
    import paddle_tpu as paddle
    from paddle_tpu.device import plugin

    monkeypatch.setitem(plugin._REGISTERED, "mynpu", "/fake/libpjrt.so")
    assert paddle.device.is_compiled_with_custom_device("mynpu")
    assert "mynpu" in paddle.device.get_all_custom_device_type()
    place = paddle.device.set_device("mynpu")
    assert place is not None
    paddle.device.set_device("cpu")
