"""Gradient clipping (ref: python/paddle/nn/clip.py, upstream layout,
unverified). Clip objects are attached to optimizers (grad_clip=...) and
applied to [(param, grad)] lists before the update; the functional form is
reused inside jitted train steps and by HybridParallelClipGrad (which psums
the squared norm across mesh axes first)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError

    def _clip_fn(self):
        """Pure (grads_pytree -> grads_pytree) used by jitted steps."""
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._data, self.min, self.max),
                                  stop_gradient=True)))
        return out

    def _clip_fn(self):
        import jax

        def fn(grads):
            return jax.tree_util.tree_map(
                lambda g: jnp.clip(g, self.min, self.max), grads)

        return fn


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
            factor = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                 1.0)
            out.append((p, Tensor((g._data * factor).astype(g._data.dtype),
                                  stop_gradient=True)))
        return out

    def _clip_fn(self):
        import jax

        def fn(grads):
            def clip_one(g):
                norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
                factor = jnp.minimum(
                    self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
                return (g * factor).astype(g.dtype)

            return jax.tree_util.tree_map(clip_one, grads)

        return fn


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    @staticmethod
    def _global_norm_sq(datas):
        return sum(jnp.sum(jnp.square(d.astype(jnp.float32)))
                   for d in datas)

    def __call__(self, params_grads):
        clippable = [(p, g) for p, g in params_grads
                     if g is not None and getattr(p, "need_clip", True)]
        if not clippable:
            return params_grads
        gnorm_sq = self._global_norm_sq([g._data for _, g in clippable])
        gnorm = jnp.sqrt(gnorm_sq)
        factor = jnp.minimum(self.clip_norm / jnp.maximum(gnorm, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, Tensor((g._data * factor).astype(
                    g._data.dtype), stop_gradient=True)))
        return out

    def _clip_fn(self):
        import jax

        def fn(grads):
            leaves = jax.tree_util.tree_leaves(grads)
            gnorm = jnp.sqrt(sum(
                jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))
            factor = jnp.minimum(self.clip_norm / jnp.maximum(gnorm, 1e-12),
                                 1.0)
            return jax.tree_util.tree_map(
                lambda g: (g * factor).astype(g.dtype), grads)

        return fn


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.power(sum(
            jnp.sum(jnp.power(jnp.abs(g._data.astype(jnp.float32)),
                              norm_type)) for g in grads), 1.0 / norm_type)
    factor = jnp.minimum(max_norm / jnp.maximum(total, 1e-12), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._data = (p.grad._data * factor).astype(p.grad._data.dtype)
    return Tensor(total)
