"""paddle.incubate — experimental APIs (MoE, fused layers).

Ref: python/paddle/incubate/ (upstream layout, unverified — mount empty).
"""
from . import distributed  # noqa: F401
from . import nn  # noqa: F401
from . import asp  # noqa: F401
from . import autograd  # noqa: F401
from . import optimizer  # noqa: F401
from .optimizer import LookAhead, ModelAverage  # noqa: F401
