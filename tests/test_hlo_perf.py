"""Chip-independent performance gates (VERDICT r4 #1).

Most of the remaining MFU risk — fusion structure, dtype upcasts, collective
placement, donation — is visible in the compiled/lowered HLO without any TPU
hardware. Two tiers:

* Default tier (always on): cross-platform *lowering* of the exact bench
  train step (bench.make_train_step, ERNIE-base, batch 32 x seq 512, bf16
  autocast) for the TPU target via
  ``jit(step).trace(...).lower(lowering_platforms=("tpu",))``. Asserts on
  the StableHLO text: Pallas flash custom-calls present (no materialized
  softmax(qk^T)v), every matmul operand bf16 (no f32 upcasts), input
  buffers donated.

* AOT tier (``PADDLE_TPU_AOT=1``, ~6 min): full TPU *compilation* through
  the real v5e compiler pipeline — including the Mosaic kernel compiler —
  using ``jax.experimental.topologies`` device-less topologies (libtpu is
  installed; no chip needed). This discharges the "Pallas kernels are
  CPU-interpret-verified only" risk (VERDICT r4 weak #6) and checks what
  GSPMD actually emits for ZeRO-2 (reduce-scatter creation happens in the
  TPU pipeline, NOT in the CPU pipeline — verified r5) plus the HBM budget
  via ``compiled.memory_analysis()``.

Ref: SURVEY.md §6/§7; BASELINE.md north star >= 40% MFU; roofline numbers
recorded in PERF_NOTES.md.
"""
import os
import re
from collections import Counter

import numpy as np
import pytest

import jax
import jax.numpy as jnp

AOT = os.environ.get("PADDLE_TPU_AOT") == "1"

BATCH, SEQ = 32, 512


def _patch_tpu_gates(monkeypatch):
    """Make the functional layer pick the TPU kernel paths while tracing on
    the CPU host — the lowering target is TPU, the gate must agree."""
    from paddle_tpu.ops import pallas_kernels

    monkeypatch.setattr(pallas_kernels, "_on_tpu", lambda: True)


@pytest.fixture(scope="module")
def bench_step_lowered():
    """Lower the exact bench train step for the TPU target, once."""
    from paddle_tpu.ops import pallas_kernels

    orig = pallas_kernels._on_tpu
    pallas_kernels._on_tpu = lambda: True
    try:
        import paddle_tpu as paddle
        from paddle_tpu.jit.functional import extract_state
        from paddle_tpu.models import ErnieConfig, ErnieForPretraining
        import bench

        cfg = ErnieConfig.ernie_base()
        cfg.fused_mlm_loss = True   # the shipping bench config (r5)
        model = ErnieForPretraining(cfg)
        model.train()
        opt = paddle.optimizer.Adam(learning_rate=1e-4,
                                    parameters=model.parameters())
        params, buffers = extract_state(model)
        opt_state = opt.functional_state(params)

        jitted = jax.jit(bench.make_train_step(model, opt),
                         donate_argnums=(0, 1, 2))
        data = jax.ShapeDtypeStruct((BATCH, SEQ), jnp.int32)
        lowered = jitted.trace(
            params, buffers, opt_state, jnp.float32(1e-4), jnp.int32(1),
            jax.random.key(0), data, data,
        ).lower(lowering_platforms=("tpu",))
        n_leaves = len(jax.tree_util.tree_leaves((params, buffers,
                                                  opt_state)))
        return lowered.as_text(), n_leaves
    finally:
        pallas_kernels._on_tpu = orig


def test_flash_custom_call_in_bench_step(bench_step_lowered):
    """The train step must reach the Pallas flash kernel in fwd AND bwd —
    one Mosaic custom-call per layer per kernel (12 layers: fwd, dq, dkv),
    not a materialized softmax(qk^T)v."""
    txt, _ = bench_step_lowered
    assert txt.count("tpu_custom_call") >= 36, txt.count("tpu_custom_call")


def test_no_materialized_attention(bench_step_lowered):
    """No (batch, heads, seq, seq) buffer may exist at any dtype — that is
    the O(s^2) materialization flash attention exists to avoid."""
    txt, _ = bench_step_lowered
    pat = re.compile(r"tensor<%dx12x%dx%dx(f32|bf16|f16)>"
                     % (BATCH, SEQ, SEQ))
    assert not pat.search(txt)


def test_all_matmuls_bf16(bench_step_lowered):
    """Every dot_general in the step must consume bf16 operands: one f32
    matmul forfeits the MXU's bf16 rate (VERDICT r4 next #1 item (b))."""
    txt, _ = bench_step_lowered
    combos = Counter()
    for operands in re.findall(
            r"stablehlo\.dot_general[^:]*:\s*\(([^)]*)\)\s*->", txt):
        tys = re.findall(r"tensor<([^>]*)>", operands)
        combos[tuple(t.split("x")[-1] for t in tys)] += 1
    assert combos, "no dot_general found — wrong module?"
    assert set(combos) == {("bf16", "bf16")}, dict(combos)


def test_no_materialized_logits(bench_step_lowered):
    """The fused-CE head (r5) must keep the f32 (batch*seq, vocab) logits
    out of the step — only per-chunk blocks may exist. Its reappearance
    costs ~10 ms/step of copies and ~2.4 GB live (PERF_NOTES r5)."""
    txt, _ = bench_step_lowered
    n_rows = BATCH * SEQ
    assert not re.search(r"tensor<%dx18000xf32>" % n_rows, txt)
    assert not re.search(r"tensor<%dx512x18000x(f32|bf16)>" % BATCH, txt)


def test_state_buffers_donated(bench_step_lowered):
    """params/buffers/opt_state are donated (donate_argnums=(0,1,2)); the
    lowered module records each aliased input as tf.aliasing_output. Without
    donation the step holds two copies of the 1.2 GB state."""
    txt, n_leaves = bench_step_lowered
    n_aliased = txt.count("tf.aliasing_output")
    assert n_aliased >= int(0.9 * n_leaves), (n_aliased, n_leaves)


# ---------------------------------------------------------------- AOT tier

aot = pytest.mark.skipif(not AOT, reason="set PADDLE_TPU_AOT=1 (slow: runs "
                         "the real TPU compiler via libtpu topologies)")


def _topology_mesh(topology_name, axes):
    from jax.experimental import topologies

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name=topology_name)
    devs = np.array(topo.devices)
    sizes = []
    n = len(topo.devices)
    for a in axes[:-1]:
        sizes.append(1)
    sizes.append(n)
    return jax.sharding.Mesh(devs.reshape(sizes), axes), topo


@aot
def test_bench_step_compiles_with_mosaic(monkeypatch):
    """Full bench step through the real v5e compiler: every Pallas kernel in
    the step (flash fwd/bwd with in-kernel dropout, fused norms) must pass
    Mosaic compilation — the r3/r4 hardware-gate debt, discharged without a
    chip. Also enforces the HBM budget: the step must fit a 16 GB v5e."""
    _patch_tpu_gates(monkeypatch)
    from jax.experimental import topologies

    import paddle_tpu as paddle
    from paddle_tpu.jit.functional import extract_state
    from paddle_tpu.models import ErnieConfig, ErnieForPretraining
    import bench

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2")
    dev = topo.devices[0]
    sh = jax.sharding.SingleDeviceSharding(dev)

    cfg = ErnieConfig.ernie_base()
    cfg.fused_mlm_loss = True       # the shipping bench config (r5)
    model = ErnieForPretraining(cfg)
    model.train()
    opt = paddle.optimizer.Adam(learning_rate=1e-4,
                                parameters=model.parameters())
    params, buffers = extract_state(model)
    opt_state = opt.functional_state(params)

    def absify(t):
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh), t)

    jitted = jax.jit(bench.make_train_step(model, opt),
                     donate_argnums=(0, 1, 2))
    scalar = lambda dt: jax.ShapeDtypeStruct((), dt, sharding=sh)  # noqa:E731
    data = jax.ShapeDtypeStruct((BATCH, SEQ), jnp.int32, sharding=sh)
    compiled = jitted.lower(
        absify(params), absify(buffers), absify(opt_state),
        scalar(jnp.float32), scalar(jnp.int32),
        scalar(jax.random.key(0).dtype), data, data).compile()

    mem = compiled.memory_analysis()
    hbm = (mem.argument_size_in_bytes + mem.temp_size_in_bytes
           + mem.generated_code_size_in_bytes
           - mem.alias_size_in_bytes + mem.output_size_in_bytes)
    assert hbm < 16e9, f"step needs {hbm/1e9:.1f} GB > v5e 16 GB HBM"


@aot
def test_bench_step_batch64_fits_hbm(monkeypatch):
    """The fused-CE head's memory win must hold: the PLAIN (no remat)
    batch-64 step compiles within the 16 GB v5e budget (15.74 GB at
    r5 — the sweep's best-throughput config depends on this)."""
    _patch_tpu_gates(monkeypatch)
    from jax.experimental import topologies

    import paddle_tpu as paddle
    from paddle_tpu.jit.functional import extract_state
    from paddle_tpu.models import ErnieConfig, ErnieForPretraining
    import bench

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2")
    sh = jax.sharding.SingleDeviceSharding(topo.devices[0])

    cfg = ErnieConfig.ernie_base()
    cfg.fused_mlm_loss = True
    model = ErnieForPretraining(cfg)
    model.train()
    opt = paddle.optimizer.Adam(learning_rate=1e-4,
                                parameters=model.parameters())
    params, buffers = extract_state(model)
    opt_state = opt.functional_state(params)

    def absify(t):
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh),
            t)

    jitted = jax.jit(bench.make_train_step(model, opt),
                     donate_argnums=(0, 1, 2))
    scalar = lambda dt: jax.ShapeDtypeStruct((), dt, sharding=sh)  # noqa:E731
    data = jax.ShapeDtypeStruct((64, SEQ), jnp.int32, sharding=sh)
    compiled = jitted.lower(
        absify(params), absify(buffers), absify(opt_state),
        scalar(jnp.float32), scalar(jnp.int32),
        scalar(jax.random.key(0).dtype), data, data).compile()
    mem = compiled.memory_analysis()
    hbm = (mem.argument_size_in_bytes + mem.temp_size_in_bytes
           + mem.generated_code_size_in_bytes
           - mem.alias_size_in_bytes + mem.output_size_in_bytes)
    assert hbm < 16e9, (f"plain batch-64 fused step needs "
                        f"{hbm/1e9:.2f} GB > 16 GB")


@aot
def test_zero2_step_emits_reduce_scatter():
    """ZeRO-2 through the PRODUCT hapi step on an 8-chip v5e topology: the
    TPU pipeline must turn the grad all-reduce + shard-slice into
    reduce-scatter (the bandwidth halving that is stage 2's whole point).
    The CPU pipeline never creates reduce-scatter, so only this AOT tier
    can check it."""
    from types import SimpleNamespace

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.fleet.meta_parallel import (
        group_sharded_parallel)

    mesh, topo = _topology_mesh("v5e:2x4", ("sharding",))
    group = SimpleNamespace(mesh=mesh, axis_name="sharding")

    paddle.seed(7)
    net = nn.Sequential(nn.Linear(64, 256), nn.ReLU(), nn.Linear(256, 64))
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    wrapped, _ = group_sharded_parallel(net, opt, level="os_g", group=group)
    model = paddle.Model(wrapped)
    model.prepare(optimizer=opt, loss=nn.MSELoss())

    params, buffers = model._sync_state_in()
    model._ensure_opt_state(params)
    step = model._build_train_step()

    def absify(t):
        return jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), t)

    data = jax.ShapeDtypeStruct((64, 64), jnp.float32)
    compiled = step.lower(
        absify(params), absify(buffers), absify(model._opt_state),
        jax.ShapeDtypeStruct((), jnp.float32),
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.ShapeDtypeStruct((), jax.random.key(0).dtype),
        (data,), (data,)).compile()
    txt = compiled.as_text()
    assert txt.count("reduce-scatter") >= 1, (
        "ZeRO-2 step compiled without any reduce-scatter:\n"
        + "\n".join(ln for ln in txt.splitlines() if "all-reduce(" in ln))


@aot
def test_ring_attention_kernel_compiles_with_mosaic(monkeypatch):
    """The ring STEP kernel (SMEM offsets + pl.when block skip) has never
    passed Mosaic off-CPU (VERDICT r4 weak #6); compile the sep=4 ring
    attention through the real pipeline."""
    _patch_tpu_gates(monkeypatch)
    try:
        from jax import shard_map
    except ImportError:                 # jax 0.4.x: experimental home
        from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.ops import pallas_kernels as pk

    mesh, _ = _topology_mesh("v5e:2x2", ("sep",))

    def ring(q, k, v):
        return pk.ring_flash_attention_pallas(q, k, v, axis_name="sep",
                                              causal=True)

    b, s, h, d = 2, 1024, 4, 64
    spec = P(None, "sep", None, None)
    f = shard_map(ring, mesh=mesh, in_specs=(spec, spec, spec),
                  out_specs=spec)
    jitted = jax.jit(f, in_shardings=NamedSharding(mesh, spec),
                     out_shardings=NamedSharding(mesh, spec))
    x = jax.ShapeDtypeStruct((b, s, h, d), jnp.bfloat16)
    compiled = jitted.lower(x, x, x).compile()
    assert compiled.as_text().count("custom-call") >= 4


@aot
def test_moe_gather_dispatch_compiles_with_mosaic():
    """The fused MoE dispatch gather (scalar-prefetched indices + per-row
    async HBM->VMEM copies) must pass the real Mosaic compiler."""
    from paddle_tpu.ops import pallas_kernels as pk

    from jax.experimental import topologies

    topo = topologies.get_topology_desc(platform="tpu",
                                        topology_name="v5e:2x2")
    sh = jax.sharding.SingleDeviceSharding(topo.devices[0])
    src = jax.ShapeDtypeStruct((1024, 512), jnp.bfloat16, sharding=sh)
    idx = jax.ShapeDtypeStruct((2048,), jnp.int32, sharding=sh)
    compiled = jax.jit(
        lambda s, i: pk.gather_rows(s, i)).lower(src, idx).compile()
    assert compiled.as_text().count("custom-call") >= 1
