"""paddle.quantization — PTQ observers + QAT fake-quant (int8 simulation).

Ref: python/paddle/quantization/ (upstream layout, unverified — mount empty).
Observers are real statistics collectors (abs-max, EMA, percentile-histogram)
producing scales; fake-quant is real round-to-grid quantize-dequantize with a
straight-through estimator (x + stop_grad(qdq(x) - x)) so QAT trains through
the rounding. PTQ inserts observers via Layer forward hooks; convert() bakes
observed scales into QuantedLayers that run the qdq math at inference.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn import Layer

__all__ = [
    "QuantConfig", "PTQ", "QAT", "quanter",
    "AbsmaxObserver", "EMAObserver", "HistObserver",
    "FakeQuanterWithAbsMaxObserver", "FakeQuanterChannelWiseAbsMaxObserver",
    "quantize_dequantize", "QuantedLinear", "QuantedConv2D",
]


def _data(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def quantize_dequantize(x, scale, bits: int = 8, axis: Optional[int] = None):
    """Round to the int grid and back, STE gradient (identity)."""
    data = _data(x)
    qmax = float(2 ** (bits - 1) - 1)
    s = _data(scale)
    if axis is not None:
        shape = [1] * data.ndim
        shape[axis] = -1
        s = s.reshape(shape)
    s = jnp.maximum(s, 1e-9)
    q = jnp.clip(jnp.round(data / s * qmax), -qmax, qmax) / qmax * s
    out = data + jax.lax.stop_gradient(q - data)
    return Tensor(out) if isinstance(x, Tensor) else out


# ------------------------------------------------------------------ observers

class _ObserverLayer(Layer):
    """Collects statistics on every forward; scales() after calibration."""

    def __init__(self, quant_bits: int = 8):
        super().__init__()
        self.quant_bits = quant_bits
        self._observed = False

    def forward(self, x):
        self._observe(_data(x))
        self._observed = True
        return x

    def _observe(self, data):
        raise NotImplementedError

    def scales(self) -> Tensor:
        raise NotImplementedError

    def zero_points(self) -> Tensor:
        return Tensor(jnp.zeros_like(self.scales()._data))


class AbsmaxObserver(_ObserverLayer):
    def __init__(self, quant_bits: int = 8):
        super().__init__(quant_bits)
        self._max = 0.0

    def _observe(self, data):
        self._max = max(self._max, float(jnp.max(jnp.abs(data))))

    def scales(self) -> Tensor:
        return Tensor(jnp.asarray(self._max, jnp.float32))


class EMAObserver(_ObserverLayer):
    """Moving-average abs-max (activation observer of choice for QAT)."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9):
        super().__init__(quant_bits)
        self.moving_rate = moving_rate
        self._state: Optional[float] = None

    def _observe(self, data):
        cur = float(jnp.max(jnp.abs(data)))
        if self._state is None:
            self._state = cur
        else:
            self._state = (self.moving_rate * self._state
                           + (1 - self.moving_rate) * cur)

    def scales(self) -> Tensor:
        return Tensor(jnp.asarray(self._state or 0.0, jnp.float32))


class HistObserver(_ObserverLayer):
    """Percentile scale from an accumulated |x| histogram (outlier-robust)."""

    def __init__(self, quant_bits: int = 8, bins: int = 2048,
                 percent: float = 0.999):
        super().__init__(quant_bits)
        self.bins = bins
        self.percent = percent
        self._hist = np.zeros(bins)
        self._max = 1e-9

    def _observe(self, data):
        a = np.abs(np.asarray(data)).ravel()
        cur_max = float(a.max()) if a.size else 0.0
        if cur_max > self._max:  # re-bin the old histogram into a wider range
            old_edges = np.linspace(0, self._max, self.bins + 1)
            new_edges = np.linspace(0, cur_max, self.bins + 1)
            centers = (old_edges[:-1] + old_edges[1:]) / 2
            rebinned, _ = np.histogram(centers, bins=new_edges,
                                       weights=self._hist)
            self._hist = rebinned
            self._max = cur_max
        h, _ = np.histogram(a, bins=self.bins, range=(0, self._max))
        self._hist += h

    def scales(self) -> Tensor:
        total = self._hist.sum()
        if total == 0:
            return Tensor(jnp.asarray(0.0, jnp.float32))
        cdf = np.cumsum(self._hist) / total
        idx = int(np.searchsorted(cdf, self.percent))
        edge = (idx + 1) / self.bins * self._max
        return Tensor(jnp.asarray(edge, jnp.float32))


# ---------------------------------------------------------------- fake quant

class FakeQuanterWithAbsMaxObserver(_ObserverLayer):
    """QAT activation quanter: EMA abs-max observe + qdq with STE."""

    def __init__(self, moving_rate: float = 0.9, quant_bits: int = 8,
                 **kwargs):
        super().__init__(quant_bits)
        self._obs = EMAObserver(quant_bits, moving_rate)

    def forward(self, x):
        self._obs._observe(_data(x))
        if self.training:
            return quantize_dequantize(x, self._obs.scales(),
                                       self.quant_bits)
        return quantize_dequantize(x, self._obs.scales(), self.quant_bits)

    def scales(self):
        return self._obs.scales()


class FakeQuanterChannelWiseAbsMaxObserver(_ObserverLayer):
    """Weight quanter: per-output-channel abs-max + qdq with STE."""

    def __init__(self, quant_bits: int = 8, quant_axis: int = -1, **kwargs):
        super().__init__(quant_bits)
        self.quant_axis = quant_axis
        self._scales = None

    def forward(self, w):
        data = _data(w)
        axis = self.quant_axis % data.ndim
        reduce_axes = tuple(i for i in range(data.ndim) if i != axis)
        self._scales = jnp.max(jnp.abs(data), axis=reduce_axes)
        return quantize_dequantize(w, Tensor(self._scales), self.quant_bits,
                                   axis=axis)

    def scales(self):
        return Tensor(self._scales)


quanter = FakeQuanterWithAbsMaxObserver  # paddle alias


# -------------------------------------------------------------------- config

class QuantConfig:
    """Which layers get which activation/weight quanter (paddle.quantization
    .QuantConfig shape: global default + per-layer/type overrides)."""

    def __init__(self, activation=None, weight=None):
        self.activation = activation
        self.weight = weight
        self._type_configs: Dict[type, tuple] = {}
        self._layer_configs: Dict[int, tuple] = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, (list, tuple))
                  else [layer_type]):
            self._type_configs[t] = (activation, weight)

    def add_layer_config(self, layer, activation=None, weight=None):
        for l in (layer if isinstance(layer, (list, tuple)) else [layer]):
            self._layer_configs[id(l)] = (activation, weight)

    def _config_for(self, layer):
        if id(layer) in self._layer_configs:
            return self._layer_configs[id(layer)]
        for t, cfg in self._type_configs.items():
            if isinstance(layer, t):
                return cfg
        return (self.activation, self.weight)


def _make(quanter_cls_or_obj):
    if quanter_cls_or_obj is None:
        return None
    if isinstance(quanter_cls_or_obj, type):
        return quanter_cls_or_obj()
    import copy

    return copy.deepcopy(quanter_cls_or_obj)


# ------------------------------------------------------------ quanted layers

class QuantedLinear(Layer):
    def __init__(self, linear, act_quanter, weight_quanter):
        super().__init__()
        self.inner = linear
        self.act_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        from .. import nn

        if self.act_quanter is not None:
            x = self.act_quanter(x)
        w = self.inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return nn.functional.linear(x, w, self.inner.bias)


class QuantedConv2D(Layer):
    def __init__(self, conv, act_quanter, weight_quanter):
        super().__init__()
        self.inner = conv
        self.act_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        from .. import nn

        if self.act_quanter is not None:
            x = self.act_quanter(x)
        w = self.inner.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return nn.functional.conv2d(
            x, w, self.inner.bias, stride=self.inner._stride,
            padding=self.inner._padding, dilation=self.inner._dilation,
            groups=self.inner._groups)


def _swap_quantable(model: Layer, config: QuantConfig) -> int:
    """Replace Linear/Conv2D sublayers with quanted wrappers in place."""
    from .. import nn

    n = 0
    for name, child in list(model.named_children()):
        act_q, w_q = config._config_for(child)
        if isinstance(child, nn.Linear):
            setattr(model, name,
                    QuantedLinear(child, _make(act_q), _make(w_q)))
            n += 1
        elif isinstance(child, nn.Conv2D):
            setattr(model, name,
                    QuantedConv2D(child, _make(act_q), _make(w_q)))
            n += 1
        else:
            n += _swap_quantable(child, config)
    return n


class QAT:
    """Quantization-aware training: swap in fake-quant wrappers, train."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        n = _swap_quantable(model, self.config)
        if n == 0:
            raise ValueError("no quantable (Linear/Conv2D) layers found")
        return model

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        return model  # scales live in the quanters; qdq already inline


class PTQ:
    """Post-training quantization: observe activations, then bake scales."""

    def __init__(self, config: QuantConfig):
        self.config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            import copy

            model = copy.deepcopy(model)
        cfg = QuantConfig(self.config.activation or AbsmaxObserver,
                          self.config.weight
                          or FakeQuanterChannelWiseAbsMaxObserver)
        cfg._type_configs = self.config._type_configs
        n = _swap_quantable(model, cfg)
        if n == 0:
            raise ValueError("no quantable (Linear/Conv2D) layers found")
        return model

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        """After calibration forwards: freeze observer scales into qdq."""
        for layer in model.sublayers(include_self=True):
            if isinstance(layer, (QuantedLinear, QuantedConv2D)):
                obs = layer.act_quanter
                if isinstance(obs, _ObserverLayer) and obs._observed:
                    scale = obs.scales()
                    bits = obs.quant_bits

                    class _Baked(Layer):
                        def __init__(self, s, b):
                            super().__init__()
                            self._s, self._b = s, b

                        def forward(self, x):
                            return quantize_dequantize(x, self._s, self._b)

                    layer.act_quanter = _Baked(scale, bits)
        return model
