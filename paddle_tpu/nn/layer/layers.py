"""nn.Layer — the module base class (≈ torch.nn.Module).

Ref: python/paddle/nn/layer/layers.py (upstream layout, unverified — mount
empty). Holds Parameters/buffers/sublayers with paddle's exact API surface
(create_parameter, add_sublayer, state_dict, train/eval, hooks).

TPU note: layers are stateful python objects for the imperative API; the jit
path extracts a functional (params, buffers) pytree via
paddle_tpu.jit.functionalize and re-binds it under tracing, so one Layer
definition serves both dygraph and compiled execution.
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ...core.dtype import convert_dtype, get_default_dtype
from ...core.tensor import Parameter, Tensor
from .. import initializer as I


class ParamAttr:
    """paddle.ParamAttr analog."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, I.Initializer):
            return ParamAttr(initializer=attr)
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return False
        raise TypeError(f"cannot convert {attr!r} to ParamAttr")


def make_parameter(shape, attr=None, dtype=None, is_bias=False,
                   default_initializer=None, name=None):
    """Single implementation behind Layer.create_parameter AND the free
    paddle.create_parameter: attr normalization, initializer fallback
    chain (attr > explicit default > global default > Constant/Xavier),
    optimize-attr wiring."""
    from ...core.dtype import get_default_dtype

    attr = ParamAttr._to_attr(attr)
    if attr is False:
        return None
    dtype = convert_dtype(dtype) or get_default_dtype()
    init = attr.initializer or default_initializer
    if init is None:
        init = I.global_bias_init() if is_bias else I.global_weight_init()
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierNormal()
    data = init(shape, dtype)
    p = Parameter(data, name=name or attr.name or "",
                  trainable=attr.trainable)
    p.optimize_attr["learning_rate"] = attr.learning_rate
    p.regularizer = attr.regularizer
    p.need_clip = attr.need_clip
    return p


class HookRemoveHelper:
    def __init__(self, hooks: dict, hook_id: int):
        self._hooks = hooks
        self._id = hook_id

    def remove(self):
        self._hooks.pop(self._id, None)


_LAYER_COUNTER = collections.defaultdict(int)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        cls = type(self).__name__.lower()
        _LAYER_COUNTER[cls] += 1
        self._full_name = name_scope or f"{cls}_{_LAYER_COUNTER[cls] - 1}"
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self._parameters: Dict[str, Optional[Parameter]] = \
            collections.OrderedDict()
        self._buffers: Dict[str, Optional[Tensor]] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self.training = True
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._hook_id = 0

    # --------------------------------------------------------------- params
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        return make_parameter(shape, attr=attr,
                              dtype=convert_dtype(dtype) or self._dtype,
                              is_bias=is_bias,
                              default_initializer=default_initializer)

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[name] = parameter
        if parameter is not None and not parameter.name:
            parameter.name = f"{self._full_name}.{name}"
        return parameter

    def register_buffer(self, name: str, tensor: Optional[Tensor],
                        persistable: bool = True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    # ------------------------------------------------------------ attribute
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise AttributeError("call Layer.__init__ first")
            params[name] = value
            if not value.name:
                value.name = f"{self._full_name}.{name}"
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise AttributeError("call Layer.__init__ first")
            layers[name] = value
            self.__dict__.pop(name, None)
        elif params is not None and name in params:
            params[name] = value
        elif layers is not None and name in layers:
            layers[name] = value
        elif buffers is not None and name in buffers:
            buffers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        if "_parameters" in self.__dict__ and name in self.__dict__["_parameters"]:
            return self.__dict__["_parameters"][name]
        if "_sub_layers" in self.__dict__ and name in self.__dict__["_sub_layers"]:
            return self.__dict__["_sub_layers"][name]
        if "_buffers" in self.__dict__ and name in self.__dict__["_buffers"]:
            return self.__dict__["_buffers"][name]
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        if name in self._parameters:
            del self._parameters[name]
        elif name in self._sub_layers:
            del self._sub_layers[name]
        elif name in self._buffers:
            del self._buffers[name]
        else:
            object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # ------------------------------------------------------------ iteration
    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        seen = set()
        for name, l in self._sub_layers.items():
            if l is not None and id(l) not in seen:
                seen.add(id(l))
                yield name, l

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False, layers_set=None):
        if layers_set is None:
            layers_set = set()
        if include_self and id(self) not in layers_set:
            layers_set.add(id(self))
            yield prefix, self
        for name, l in self.named_children():
            if l is None or id(l) in layers_set:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True,
                                         layers_set=layers_set)

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for lp, layer in layers:
            for name, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{lp}.{name}" if lp else name), p

    def buffers(self, include_sublayers=True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        layers = self.named_sublayers(prefix=prefix, include_self=True) \
            if include_sublayers else [(prefix, self)]
        for lp, layer in layers:
            for name, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{lp}.{name}" if lp else name), b

    # ------------------------------------------------------------ modes/etc
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    def full_name(self):
        return self._full_name

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dtype = convert_dtype(dtype)
            for _, p in self.named_parameters():
                if np.issubdtype(p.dtype, np.floating):
                    p._data = p._data.astype(dtype)
            for _, b in self.named_buffers():
                if b is not None and np.issubdtype(b.dtype, np.floating):
                    b._data = b._data.astype(dtype)
            self._dtype = dtype
        if device is not None:
            import jax

            from ...core.place import Place, set_device

            place = device if isinstance(device, Place) else None
            if place is None:
                from ...core.place import CPUPlace, TPUPlace

                place = CPUPlace(0) if str(device).startswith("cpu") \
                    else TPUPlace(0)
            dev = place.jax_device()
            for _, p in self.named_parameters():
                p._data = jax.device_put(p._data, dev)
            for _, b in self.named_buffers():
                if b is not None:
                    b._data = jax.device_put(b._data, dev)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    # ----------------------------------------------------------- state_dict
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else \
            collections.OrderedDict()
        for name, p in self.named_parameters(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            dest[name] = p
        for name, b in self.named_buffers(
                prefix=structured_name_prefix.rstrip("."),
                include_sublayers=include_sublayers):
            short = name.rsplit(".", 1)[-1]
            # find owning layer to check persistability
            dest[name] = b
        # drop non-persistable buffers
        non_persist = set()
        for lp, layer in self.named_sublayers(include_self=True):
            for bname in layer._non_persistable_buffer_names:
                non_persist.add(f"{lp}.{bname}" if lp else bname)
        for k in non_persist:
            dest.pop(k, None)
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k not in own:
                unexpected.append(k)
                continue
            target = own[k]
            arr = v._data if isinstance(v, Tensor) else np.asarray(v)
            import jax.numpy as jnp

            target._data = jnp.asarray(arr, dtype=target._data.dtype).reshape(
                target._data.shape)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -------------------------------------------------------------- forward
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self.named_children():
            child = repr(l).split("\n")
            child = [child[0]] + ["  " + c for c in child[1:]]
            lines.append(f"  ({name}): " + "\n".join(child))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
