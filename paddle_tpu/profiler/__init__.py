"""paddle.profiler — scheduling windows, RecordEvent, chrome-trace export,
summary tables.

Ref: python/paddle/profiler/{profiler,profiler_statistic}.py +
paddle/fluid/platform/profiler/ (upstream layout, unverified — mount empty).
Paddle merges a host tracer (RecordEvent instrumentation) with a CUPTI device
tracer. The TPU-native split: the HOST tracer is ours (timestamped event
intervals per thread, chrome-trace exportable, summarizable), and the DEVICE
tracer is jax.profiler (XPlane/TensorBoard format) started/stopped around the
active window. RecordEvent also enters a jax.profiler.TraceAnnotation so host
spans line up inside the device timeline.
"""
from __future__ import annotations

import json
import os
import threading
import time
from enum import Enum
from typing import Callable, Iterable, Optional

__all__ = [
    "Profiler", "ProfilerState", "ProfilerTarget", "RecordEvent",
    "add_host_span",
    "make_scheduler", "export_chrome_tracing", "export_protobuf",
    "load_profiler_result", "SortedKeys", "SummaryView",
]


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3  # last active step of a window


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class SortedKeys(Enum):
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6


# ---------------------------------------------------------------- host tracer

class _HostEvent:
    __slots__ = ("name", "start", "end", "tid", "event_type")

    def __init__(self, name, start, end, tid, event_type):
        self.name = name
        self.start = start
        self.end = end
        self.tid = tid
        self.event_type = event_type


class _HostTracer:
    """Process-wide host event sink. Spans are recorded by the NATIVE C++
    tracer (core/native/host_tracer.cc — the upstream host_tracer analog)
    when it compiles, with this Python list as the fallback sink and the
    merge point at drain()."""

    def __init__(self):
        self.events: list[_HostEvent] = []
        self.armed = False
        self._lock = threading.Lock()

    def set_armed(self, armed: bool):
        self.armed = armed
        from . import native_tracer

        if native_tracer.available():
            native_tracer.set_armed(armed)

    def add(self, ev: _HostEvent):
        with self._lock:
            self.events.append(ev)

    def drain(self) -> list:
        from . import native_tracer

        with self._lock:
            out = self.events
            self.events = []
        for name, start, end, tid in native_tracer.drain():
            out.append(_HostEvent(name, start, end, tid, "UserDefined"))
        out.sort(key=lambda e: e.start)
        return out


_HOST_TRACER = _HostTracer()


def add_host_span(name: str, start: float, end: float, tid=None,
                  event_type: str = "UserDefined") -> None:
    """Record an already-completed host span with explicit perf_counter
    timestamps into the armed profiler window (no-op when no window is
    armed). The observability LifecycleTracker folds per-request serving
    lifecycle spans into chrome-trace exports through this, alongside
    RecordEvent spans (the native tracer's drain is calibrated onto the
    same perf_counter timeline, so the two sinks merge cleanly)."""
    if not _HOST_TRACER.armed:
        return
    _HOST_TRACER.add(_HostEvent(
        name, float(start), float(end),
        tid if tid is not None else threading.get_ident(), event_type))


class RecordEvent:
    """Context manager / start-stop host span (paddle.profiler.RecordEvent).

    Usable as `with RecordEvent('fwd'): ...` or begin()/end(). Also enters a
    jax.profiler TraceAnnotation so the span shows inside device traces.
    """

    def __init__(self, name: str, event_type: str = "UserDefined"):
        self.name = name
        self.event_type = event_type
        self._start: Optional[float] = None
        self._annotation = None

    def begin(self):
        from . import native_tracer

        if _HOST_TRACER.armed and native_tracer.available():
            self._native_t0 = native_tracer.now_ns()
        else:
            self._native_t0 = None
        self._start = time.perf_counter()
        try:
            import jax.profiler as jp

            self._annotation = jp.TraceAnnotation(self.name)
            self._annotation.__enter__()
        except Exception:  # noqa: BLE001 — device annotation is optional;
            # the host-side span still records either way
            self._annotation = None
        return self

    def end(self):
        if self._annotation is not None:
            self._annotation.__exit__(None, None, None)
            self._annotation = None
        if self._start is None:
            return
        if getattr(self, "_native_t0", None) is not None:
            from . import native_tracer

            native_tracer.record(native_tracer.intern(self.name),
                                 self._native_t0, native_tracer.now_ns())
            self._native_t0 = None
        elif _HOST_TRACER.armed:
            _HOST_TRACER.add(_HostEvent(
                self.name, self._start, time.perf_counter(),
                threading.get_ident(), self.event_type))
        self._start = None

    __enter__ = begin

    def __exit__(self, *exc):
        self.end()
        return False


# ----------------------------------------------------------------- scheduler

def make_scheduler(*, closed: int, ready: int, record: int,
                   repeat: int = 0, skip_first: int = 0
                   ) -> Callable[[int], ProfilerState]:
    """Step-number -> state, cycling (closed, ready, record) `repeat` times
    (0 = forever), after `skip_first` warm steps. Paddle/torch-compatible."""
    if record <= 0:
        raise ValueError("record window must be >= 1")
    cycle = closed + ready + record

    def schedule(step: int) -> ProfilerState:
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        if repeat > 0 and step >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = step % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def _default_schedule(step: int) -> ProfilerState:
    return ProfilerState.RECORD  # profile everything until stop()


# ------------------------------------------------------------------ exporters

def export_chrome_tracing(dir_name: str, worker_name: str = None
                          ) -> Callable[["Profiler"], None]:
    """on_trace_ready callback: write chrome://tracing JSON per window."""

    def handle(prof: "Profiler"):
        os.makedirs(dir_name, exist_ok=True)
        fname = (f"{worker_name or 'worker'}_pid{os.getpid()}"
                 f"_step{prof.step_num}.pt.trace.json")
        path = os.path.join(dir_name, fname)
        trace_events = []
        for ev in prof._window_events:
            trace_events.append({
                "name": ev.name, "ph": "X", "cat": ev.event_type,
                "ts": ev.start * 1e6, "dur": (ev.end - ev.start) * 1e6,
                "pid": os.getpid(), "tid": ev.tid,
            })
        with open(path, "w") as f:
            json.dump({"traceEvents": trace_events,
                       "displayTimeUnit": "ms"}, f)
        prof._last_export = path

    return handle


def export_protobuf(dir_name: str, worker_name: str = None):
    """Device traces already land in jax.profiler's protobuf (XPlane) format
    under the profiler's log dir; this callback just notes the path."""

    def handle(prof: "Profiler"):
        prof._last_export = prof._device_trace_dir

    return handle


def load_profiler_result(filename: str):
    with open(filename) as f:
        return json.load(f)


# ------------------------------------------------------------------- profiler

class Profiler:
    """paddle.profiler.Profiler over the host tracer + jax.profiler.

    with Profiler(scheduler=make_scheduler(closed=1, ready=1, record=2),
                  on_trace_ready=export_chrome_tracing('./log')) as p:
        for batch in loader:
            train_step(batch)
            p.step()
    """

    def __init__(self, *, targets: Optional[Iterable] = None,
                 scheduler=None, on_trace_ready: Optional[Callable] = None,
                 record_shapes: bool = False, profile_memory: bool = False,
                 timer_only: bool = False, **kwargs):
        if isinstance(scheduler, (tuple, list)):
            lo, hi = scheduler
            scheduler = make_scheduler(closed=lo, ready=0, record=hi - lo,
                                       repeat=1)
        self.scheduler = scheduler or _default_schedule
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.step_num = 0
        self.current_state = ProfilerState.CLOSED
        self._window_events: list = []
        self._all_events: list = []
        self._step_times: list = []
        self._last_step_ts: Optional[float] = None
        self._device_tracing = False
        self._device_trace_dir = None
        self._last_export = None

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self.current_state = self.scheduler(self.step_num)
        self._transition(ProfilerState.CLOSED, self.current_state)
        self._last_step_ts = time.perf_counter()
        return self

    def stop(self):
        self._transition(self.current_state, ProfilerState.CLOSED,
                         closing=True)
        self.current_state = ProfilerState.CLOSED

    def step(self, num_samples: Optional[int] = None):
        now = time.perf_counter()
        if self._last_step_ts is not None:
            self._step_times.append(now - self._last_step_ts)
        self._last_step_ts = now
        prev = self.current_state
        self.step_num += 1
        self.current_state = self.scheduler(self.step_num)
        self._transition(prev, self.current_state)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- state machinery ----------------------------------------------------
    def _recording(self, state):
        return state in (ProfilerState.RECORD,
                         ProfilerState.RECORD_AND_RETURN)

    def _transition(self, prev, new, closing=False):
        was = self._recording(prev)
        now = self._recording(new) and not closing
        if not was and now:
            self._arm()
        window_closed = was and (not now or
                                 prev == ProfilerState.RECORD_AND_RETURN)
        if window_closed:
            self._disarm()
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)
            self._window_events = []
            if now:  # back-to-back windows (RECORD_AND_RETURN -> RECORD)
                self._arm()

    def _arm(self):
        _HOST_TRACER.set_armed(True)
        if not self.timer_only:
            try:
                import jax.profiler as jp

                self._device_trace_dir = os.path.join(
                    os.environ.get("PADDLE_TPU_PROFILE_DIR", "/tmp"),
                    f"paddle_tpu_profile_{os.getpid()}_{self.step_num}")
                jp.start_trace(self._device_trace_dir)
                self._device_tracing = True
            except Exception as e:  # noqa: BLE001 — degrade to host-only,
                # but LOUDLY: the user asked for a device trace, and a
                # silent fall-through here is the PR 5 degradation shape
                import warnings

                warnings.warn(
                    f"device trace unavailable ({type(e).__name__}: {e}); "
                    f"profiler continues with host-side timing only",
                    RuntimeWarning, stacklevel=2)
                self._device_tracing = False

    def _disarm(self):
        _HOST_TRACER.set_armed(False)
        evs = _HOST_TRACER.drain()
        self._window_events.extend(evs)
        self._all_events.extend(evs)
        if self._device_tracing:
            try:
                import jax.profiler as jp

                jp.stop_trace()
            except Exception:  # noqa: BLE001 — stop is best-effort; the
                # trace dir may hold a partial trace after a device fault
                pass
            self._device_tracing = False

    # -- reporting ----------------------------------------------------------
    def export(self, path: str, format: str = "json"):
        export_chrome_tracing(os.path.dirname(path) or ".",
                              os.path.basename(path))(self)

    def summary(self, sorted_by: SortedKeys = SortedKeys.CPUTotal,
                op_detail: bool = True, thread_sep: bool = False,
                time_unit: str = "ms", views=None) -> str:
        """Event statistics table (profiler_statistic analog)."""
        unit = {"s": 1.0, "ms": 1e3, "us": 1e6}[time_unit]
        stats = {}
        for ev in self._all_events:
            tot, cnt, mx = stats.get(ev.name, (0.0, 0, 0.0))
            d = ev.end - ev.start
            stats[ev.name] = (tot + d, cnt + 1, max(mx, d))
        order = sorted(stats.items(),
                       key=lambda kv: kv[1][0], reverse=True)
        lines = [
            f"{'Name':<40}{'Calls':>8}{'Total(' + time_unit + ')':>14}"
            f"{'Avg(' + time_unit + ')':>14}{'Max(' + time_unit + ')':>14}",
            "-" * 90,
        ]
        for name, (tot, cnt, mx) in order:
            lines.append(f"{name[:39]:<40}{cnt:>8}{tot * unit:>14.3f}"
                         f"{tot / cnt * unit:>14.3f}{mx * unit:>14.3f}")
        if self._step_times:
            st = self._step_times
            lines += ["-" * 90,
                      f"steps: {len(st)}  avg {sum(st) / len(st) * unit:.3f}"
                      f"{time_unit}  min {min(st) * unit:.3f}{time_unit}  "
                      f"max {max(st) * unit:.3f}{time_unit}"]
        return "\n".join(lines)


def profiler_summary(prof: Profiler, **kwargs) -> str:
    return prof.summary(**kwargs)
