"""paddle.utils.download — dataset/weights fetch with local-cache honor.

Ref: python/paddle/utils/download.py (upstream layout, unverified — mount
empty). This environment has zero egress, so get_weights_path_from_url
resolves ONLY against the local cache (~/.cache/paddle_tpu by default or
PADDLE_TPU_HOME); a miss raises with a clear offline message instead of
hanging on a socket.
"""
from __future__ import annotations

import hashlib
import os
import shutil

__all__ = ["get_weights_path_from_url", "get_path_from_url", "cached_path"]

WEIGHTS_HOME = os.path.join(
    os.environ.get("PADDLE_TPU_HOME",
                   os.path.expanduser("~/.cache/paddle_tpu")), "weights")


def _md5check(path: str, md5sum: str = None) -> bool:
    if md5sum is None:
        return True
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest() == md5sum


def cached_path(url: str, root_dir: str = WEIGHTS_HOME) -> str:
    fname = os.path.basename(url.split("?")[0])
    return os.path.join(root_dir, fname)


def get_path_from_url(url: str, root_dir: str, md5sum: str = None,
                      check_exist: bool = True) -> str:
    path = cached_path(url, root_dir)
    if os.path.exists(path) and _md5check(path, md5sum):
        return path
    raise RuntimeError(
        f"{url} is not in the local cache ({path}) and this environment has "
        f"no network access. Pre-populate the cache or set PADDLE_TPU_HOME.")


def get_weights_path_from_url(url: str, md5sum: str = None) -> str:
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
