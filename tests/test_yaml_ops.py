"""ops.yaml codegen layer: generated ops vs NumPy references, autograd,
static capture, Tensor-method binding (SURVEY §2.4 YAML single source)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.tensor as T
from paddle_tpu import static
from paddle_tpu.ops.registry import OPS
from paddle_tpu.ops.yaml_ops import GENERATED, METHOD_SPECS


def _t(a, sg=True):
    t = paddle.to_tensor(np.asarray(a))
    t.stop_gradient = sg
    return t


class TestGeneratedSurface:
    def test_all_yaml_ops_registered_and_exported(self):
        assert len(GENERATED) >= 50
        for name in GENERATED:
            assert name in OPS
            assert callable(getattr(T, name))

    def test_method_binding(self):
        t = _t(np.float32([1.0, 2.0]))
        for meth in ("exp2", "sgn", "signbit", "diff"):
            assert meth in METHOD_SPECS
            assert hasattr(t, meth)
        np.testing.assert_allclose(t.exp2().numpy(), [2.0, 4.0])


class TestNumerics:
    def test_elementwise_family(self):
        x = np.float32([0.5, 1.0, 2.0])
        y = np.float32([1.5, 2.0, 0.5])
        np.testing.assert_allclose(T.exp2(_t(x)).numpy(), np.exp2(x),
                                   rtol=1e-6)
        np.testing.assert_allclose(T.logaddexp2(_t(x), _t(y)).numpy(),
                                   np.logaddexp2(x, y), rtol=1e-6)
        np.testing.assert_allclose(T.nextafter(_t(x), _t(y)).numpy(),
                                   np.nextafter(x, y))
        np.testing.assert_allclose(
            T.xlogy(_t(x), _t(y)).numpy(), x * np.log(y), rtol=1e-6)

    def test_int_family(self):
        a = np.int32([12, 18, 7])
        b = np.int32([8, 12, 21])
        np.testing.assert_array_equal(T.gcd(_t(a), _t(b)).numpy(),
                                      np.gcd(a, b))
        np.testing.assert_array_equal(T.lcm(_t(a), _t(b)).numpy(),
                                      np.lcm(a, b))

    def test_inf_sign_family(self):
        x = np.float32([-np.inf, -1.0, 0.0, np.inf])
        np.testing.assert_array_equal(T.isneginf(_t(x)).numpy(),
                                      np.isneginf(x))
        np.testing.assert_array_equal(T.isposinf(_t(x)).numpy(),
                                      np.isposinf(x))
        np.testing.assert_array_equal(T.signbit(_t(x)).numpy(),
                                      np.signbit(x))

    def test_frexp_multi_output(self):
        x = np.float32([0.5, 4.0, 12.0])
        m, e = T.frexp(_t(x))
        rm, re = np.frexp(x)
        np.testing.assert_allclose(m.numpy(), rm)
        np.testing.assert_array_equal(e.numpy(), re)

    def test_quantile_and_nanquantile(self):
        x = np.float32([[1, 2, 3, 4], [5, 6, 7, 8]])
        np.testing.assert_allclose(
            T.quantile(_t(x), 0.25, axis=1).numpy(),
            np.quantile(x, 0.25, axis=1), rtol=1e-6)
        xn = x.copy()
        xn[0, 0] = np.nan
        np.testing.assert_allclose(
            T.nanquantile(_t(xn), 0.5, axis=1).numpy(),
            np.nanquantile(xn, 0.5, axis=1), rtol=1e-6)

    def test_kthvalue_and_mode(self):
        x = np.float32([[3, 1, 2], [9, 9, 1]])
        v, i = T.kthvalue(_t(x), 2, axis=1)
        np.testing.assert_allclose(v.numpy(), [2.0, 9.0])
        mv, _ = T.mode(_t(x), axis=1)
        np.testing.assert_allclose(mv.numpy(), [1.0, 9.0])
        # keepdim: BOTH outputs carry the kept axis (paddle contract)
        vk, ik = T.kthvalue(_t(x), 2, axis=1, keepdim=True)
        assert vk.shape == [2, 1] and ik.shape == [2, 1]
        mk, mik = T.mode(_t(x), axis=1, keepdim=True)
        assert mk.shape == [2, 1] and mik.shape == [2, 1]

    def test_cdist_pdist_chebyshev_and_hamming(self):
        x = np.float32([[0.0, 0.0], [0.5, 3.0]])
        inf_d = T.cdist(_t(x), _t(x), p=float("inf")).numpy()
        np.testing.assert_allclose(inf_d, [[0.0, 3.0], [3.0, 0.0]])
        zero_d = T.cdist(_t(x), _t(x), p=0).numpy()
        np.testing.assert_allclose(zero_d, [[0.0, 2.0], [2.0, 0.0]])
        np.testing.assert_allclose(
            T.pdist(_t(x), p=float("inf")).numpy(), [3.0])

    def test_trapezoid_family(self):
        y = np.float32([1, 2, 3, 4])
        np.testing.assert_allclose(T.trapezoid(_t(y)).numpy(),
                                   np.trapezoid(y), rtol=1e-6)
        ct = T.cumulative_trapezoid(_t(y)).numpy()
        np.testing.assert_allclose(ct, [1.5, 4.0, 7.5], rtol=1e-6)

    def test_stack_split_family(self):
        a = np.float32([[1, 2], [3, 4]])
        np.testing.assert_array_equal(
            T.hstack([_t(a), _t(a)]).numpy(), np.hstack([a, a]))
        np.testing.assert_array_equal(
            T.vstack([_t(a), _t(a)]).numpy(), np.vstack([a, a]))
        np.testing.assert_array_equal(
            T.column_stack([_t(a[:, 0]), _t(a[:, 1])]).numpy(), a)
        parts = T.tensor_split(_t(np.arange(7)), 3)
        np.testing.assert_array_equal(parts[0].numpy(), [0, 1, 2])
        np.testing.assert_array_equal(parts[2].numpy(), [5, 6])

    def test_index_ops(self):
        x = np.zeros((3, 4), np.float32)
        idx = np.int32([0, 2])
        out = T.index_fill(_t(x), _t(idx), 0, 5.0).numpy()
        assert out[0].sum() == 20 and out[1].sum() == 0
        add = T.index_add(_t(x), _t(idx), 0,
                          _t(np.ones((2, 4), np.float32))).numpy()
        np.testing.assert_array_equal(add[idx], np.ones((2, 4)))

    def test_linalg_family(self):
        rng = np.random.RandomState(0)
        a = rng.randn(3, 3).astype("float32")
        sym = a @ a.T + 3 * np.eye(3, dtype="float32")
        np.testing.assert_allclose(T.eigvalsh(_t(sym)).numpy(),
                                   np.linalg.eigvalsh(sym), rtol=1e-4)
        b = rng.randn(3, 2).astype("float32")
        np.testing.assert_allclose(
            T.addmm(_t(np.ones((3, 2), np.float32)), _t(a), _t(b),
                    beta=2.0, alpha=0.5).numpy(),
            2.0 + 0.5 * (a @ b), rtol=1e-5)
        np.testing.assert_allclose(
            T.multi_dot([_t(a), _t(a), _t(b)]).numpy(),
            np.linalg.multi_dot([a, a, b]), rtol=2e-4, atol=1e-5)
        x = rng.randn(4, 3).astype("float32")
        d = T.cdist(_t(x), _t(x)).numpy()
        ref = np.sqrt(((x[:, None] - x[None]) ** 2).sum(-1))
        np.testing.assert_allclose(d, ref, rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(T.pdist(_t(x)).numpy(),
                                   ref[np.triu_indices(4, 1)], rtol=1e-4,
                                   atol=1e-5)

    def test_stat_family(self):
        rng = np.random.RandomState(2)
        x = rng.randn(3, 10).astype("float32")
        np.testing.assert_allclose(T.corrcoef(_t(x)).numpy(),
                                   np.corrcoef(x), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(T.cov(_t(x)).numpy(), np.cov(x),
                                   rtol=1e-4, atol=1e-5)

    def test_misc(self):
        np.testing.assert_array_equal(
            T.vander(_t(np.float32([1, 2, 3]))).numpy(),
            np.vander(np.float32([1, 2, 3])))
        x = np.arange(24, dtype=np.float32).reshape(2, 12)
        np.testing.assert_array_equal(
            T.unflatten(_t(x), 1, [3, 4]).numpy(), x.reshape(2, 3, 4))
        np.testing.assert_array_equal(
            T.bucketize(_t(np.float32([0.5, 2.5])),
                        _t(np.float32([1, 2, 3]))).numpy(), [0, 2])
        np.testing.assert_allclose(
            T.renorm(_t(np.float32([[3, 4], [0.3, 0.4]])), 2.0, 0,
                     1.0).numpy(),
            [[0.6, 0.8], [0.3, 0.4]], rtol=1e-5)


class TestAutogradAndStatic:
    def test_autograd_through_generated_op(self):
        x = _t(np.float32([1.0, 2.0]), sg=False)
        y = T.exp2(x).sum()
        y.backward()
        np.testing.assert_allclose(
            x.grad.numpy(), np.exp2([1.0, 2.0]) * np.log(2), rtol=1e-5)

    def test_static_capture_of_generated_op(self):
        static.enable_static()
        main = static.Program()
        try:
            with static.program_guard(main, static.Program()):
                x = static.data("x", [None, 3], "float32")
                out = T.exp2(x)
        finally:
            static.disable_static()
        exe = static.Executor()
        xv = np.float32([[0.0, 1.0, 3.0]])
        got, = exe.run(main, feed={"x": xv}, fetch_list=[out])
        np.testing.assert_allclose(got, np.exp2(xv), rtol=1e-6)

    def test_amp_list_declaration_has_runtime_effect(self):
        """The ops.yaml amp: field must actually steer autocast — a black
        op keeps fp32 inputs fp32 even under O2."""
        from paddle_tpu import amp

        assert OPS["exp2"].amp_list == "black"
        assert OPS["eigvalsh"].amp_list == "black"
        sym = np.eye(3, dtype="float32") * 4.0
        with amp.auto_cast(level="O2", dtype="bfloat16"):
            out = T.eigvalsh(_t(sym))
        assert str(out.dtype) in ("float32", "paddle.float32"), out.dtype
        np.testing.assert_allclose(out.numpy(), [4.0, 4.0, 4.0], rtol=1e-5)

    def test_eager_only_rejected_by_static_capture(self):
        from paddle_tpu.ops.registry import OPS as _OPS, register_op

        @register_op("_test_eager_only", eager_only=True)
        def _test_eager_only(x):
            return x
        try:
            static.enable_static()
            main = static.Program()
            try:
                with static.program_guard(main, static.Program()):
                    x = static.data("x", [2], "float32")
                    from paddle_tpu.core.dispatch import apply_op

                    with pytest.raises(NotImplementedError,
                                       match="data-dependent"):
                        apply_op(_OPS["_test_eager_only"], x)
            finally:
                static.disable_static()
        finally:
            del _OPS["_test_eager_only"]


class TestRound3BreadthOps:
    """Round-3 API-breadth additions (cummin/isin/nanmedian/scatter family/
    combinations/unique_consecutive/histogramdd/special fns)."""

    def test_cummin_matches_numpy(self, rng):
        x = rng.standard_normal(17).astype(np.float32)
        v, i = paddle.cummin(_t(x))
        np.testing.assert_allclose(v.numpy(), np.minimum.accumulate(x),
                                   rtol=1e-6)
        # indices point at the first occurrence of each running min
        np.testing.assert_array_equal(x[i.numpy()], np.minimum.accumulate(x))

    def test_cummin_ties_keep_first_index(self):
        v, i = paddle.cummin(_t(np.float32([2.0, 1.0, 1.0, 1.0])))
        np.testing.assert_array_equal(i.numpy(), [0, 1, 1, 1])

    def test_cummin_axis(self, rng):
        x = rng.standard_normal((3, 5)).astype(np.float32)
        v, i = paddle.cummin(_t(x), axis=1)
        np.testing.assert_allclose(v.numpy(),
                                   np.minimum.accumulate(x, axis=1),
                                   rtol=1e-6)

    def test_isin_and_invert(self):
        x = _t(np.array([1, 2, 3, 4]))
        np.testing.assert_array_equal(
            paddle.isin(x, _t(np.array([2, 4]))).numpy(),
            [False, True, False, True])
        np.testing.assert_array_equal(
            paddle.isin(x, _t(np.array([2, 4])), invert=True).numpy(),
            [True, False, True, False])

    def test_ldexp(self):
        out = paddle.ldexp(_t(np.float32([1.0, 2.0])),
                           _t(np.array([2, 3], np.int32)))
        np.testing.assert_allclose(out.numpy(), [4.0, 16.0])

    def test_nanmedian(self):
        x = _t(np.float32([[1.0, np.nan, 3.0], [4.0, 5.0, np.nan]]))
        np.testing.assert_allclose(paddle.nanmedian(x).numpy(), 3.5)
        np.testing.assert_allclose(paddle.nanmedian(x, axis=1).numpy(),
                                   [2.0, 4.5])

    def test_bitwise_shifts(self):
        x = _t(np.array([1, 4]))
        np.testing.assert_array_equal(
            paddle.bitwise_left_shift(x, _t(np.array([2, 1]))).numpy(),
            [4, 8])
        np.testing.assert_array_equal(
            paddle.bitwise_right_shift(x, _t(np.array([0, 2]))).numpy(),
            [1, 1])

    def test_slice_scatter(self):
        out = paddle.slice_scatter(
            _t(np.zeros((2, 6), np.float32)),
            _t(np.ones((2, 2), np.float32)), [1], [1], [5], [2])
        ref = np.zeros((2, 6), np.float32)
        ref[:, 1:5:2] = 1.0
        np.testing.assert_array_equal(out.numpy(), ref)

    def test_diagonal_scatter_offsets(self):
        base = np.zeros((4, 4), np.float32)
        for off in (-1, 0, 2):
            k = 4 - abs(off)
            out = paddle.diagonal_scatter(
                _t(base), _t(np.arange(1, k + 1, dtype=np.float32)),
                offset=off)
            ref = base.copy()
            rows = np.arange(k) + (-off if off < 0 else 0)
            cols = np.arange(k) + (off if off > 0 else 0)
            ref[rows, cols] = np.arange(1, k + 1)
            np.testing.assert_array_equal(out.numpy(), ref)

    def test_combinations(self):
        out = paddle.combinations(_t(np.array([1, 2, 3])), 2)
        assert out.numpy().tolist() == [[1, 2], [1, 3], [2, 3]]
        outr = paddle.combinations(_t(np.array([1, 2])), 2,
                                   with_replacement=True)
        assert outr.numpy().tolist() == [[1, 1], [1, 2], [2, 2]]

    def test_unique_consecutive(self):
        u, inv, cnt = paddle.unique_consecutive(
            _t(np.array([1, 1, 2, 2, 2, 3, 1])), return_inverse=True,
            return_counts=True)
        np.testing.assert_array_equal(u.numpy(), [1, 2, 3, 1])
        np.testing.assert_array_equal(inv.numpy(), [0, 0, 1, 1, 1, 2, 3])
        np.testing.assert_array_equal(cnt.numpy(), [2, 3, 1, 1])

    def test_histogramdd_matches_numpy(self, rng):
        x = rng.random((30, 2)).astype(np.float32)
        hist, edges = paddle.histogramdd(_t(x), bins=4)
        ref_h, ref_e = np.histogramdd(x, bins=4)
        np.testing.assert_allclose(hist.numpy(), ref_h)
        assert len(edges) == 2  # paddle pair contract, D edge arrays
        for got, want in zip(edges, ref_e):
            np.testing.assert_allclose(got.numpy(), want, rtol=1e-5)

    def test_special_functions(self):
        import scipy.special as sp
        x = np.float32([0.5, 1.5, 2.5])
        np.testing.assert_allclose(paddle.gammaln(_t(x)).numpy(),
                                   sp.gammaln(x), rtol=1e-5)
        np.testing.assert_allclose(paddle.polygamma(_t(x), 1).numpy(),
                                   sp.polygamma(1, x), rtol=1e-4)
        np.testing.assert_allclose(paddle.i0e(_t(x)).numpy(), sp.i0e(x),
                                   rtol=1e-5)
        np.testing.assert_allclose(paddle.i1(_t(x)).numpy(), sp.i1(x),
                                   rtol=1e-5)

    def test_cummin_grad_flows(self):
        x = _t(np.float32([3.0, 1.0, 2.0]), sg=False)
        v, _ = paddle.cummin(x)
        v.sum().backward()
        # d(sum of running min)/dx: x0 contributes once, x1 twice, x2 never
        np.testing.assert_allclose(x.grad.numpy(), [1.0, 2.0, 0.0])


def test_yaml_is_the_single_source_of_truth():
    """r5: every registered op comes from ops.yaml (inline impl or a
    kernel: reference) — the decorator-only registration path is retired
    (SURVEY §2.4; VERDICT r4 next #3)."""
    from paddle_tpu.ops.registry import OPS

    assert set(OPS) == set(GENERATED), (
        sorted(set(OPS) ^ set(GENERATED)))
    assert len(OPS) == 397
