#!/usr/bin/env python3
"""graftlint CLI — run the repo's AST hazard rules and gate on the baseline.

    python tools/graftlint.py paddle_tpu                 # the tier-1 gate
    python tools/graftlint.py paddle_tpu --format json   # machine-readable
    python tools/graftlint.py --rule SWALLOWED-API serving/engine.py
    python tools/graftlint.py paddle_tpu --baseline-update

Exit codes: 0 clean (no unbaselined findings, no parse errors), 1 findings
or parse errors, 2 usage error.

The analysis package is pure stdlib; this entry point loads it WITHOUT
importing `paddle_tpu` (which would pull in jax) so linting stays
sub-second and backend-free — cheap enough for the fast lane and for
bench.py's non-fatal `lint` phase.
"""
import argparse
import importlib.util
import json
import os
import sys
import time

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools", "graftlint_baseline.json")

# loaded under a private top-level name so nothing touches the real
# `paddle_tpu` package namespace (no stub parents poisoning sys.modules,
# no breakage for a later full `import paddle_tpu` in the same process)
_PKG_NAME = "_graftlint_analysis"


def load_analysis():
    """Load paddle_tpu/analysis as a standalone stdlib-only package."""
    if "paddle_tpu" in sys.modules:  # already paid for; reuse the real one
        import paddle_tpu.analysis
        return paddle_tpu.analysis
    mod = sys.modules.get(_PKG_NAME)
    if mod is not None:
        return mod
    pkg_dir = os.path.join(REPO_ROOT, "paddle_tpu", "analysis")
    spec = importlib.util.spec_from_file_location(
        _PKG_NAME, os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules[_PKG_NAME] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(_PKG_NAME, None)
        raise
    return mod


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based JAX-hazard static analyzer for this repo")
    p.add_argument("paths", nargs="*", default=["paddle_tpu"],
                   help="files/directories to analyze (default: paddle_tpu)")
    p.add_argument("--rule", action="append", default=None, metavar="NAME",
                   help="run only this rule (repeatable; accepts aliases "
                        "like BLE001)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE, metavar="PATH",
                   help="baseline file (default: tools/graftlint_baseline"
                        ".json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report every finding, ignoring the baseline")
    p.add_argument("--baseline-update", action="store_true",
                   help="rewrite the baseline from current findings, "
                        "keeping reasons for surviving fingerprints and "
                        "preserving stale entries (add --prune-stale to "
                        "drop them)")
    p.add_argument("--prune-stale", action="store_true",
                   help="drop baseline entries whose fingerprint no "
                        "longer matches any finding, printing each "
                        "pruned entry; combines with --baseline-update "
                        "or rewrites the baseline in place on its own")
    p.add_argument("--format", choices=("text", "json", "sarif"),
                   default="text")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule set and exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    analysis = load_analysis()

    if args.list_rules:
        for rule in analysis.all_rules():
            codes = ", ".join(rule.codes)
            print(f"{codes}\n    {rule.description}")
        return 0

    try:
        rules = ([analysis.get_rule(n) for n in args.rule]
                 if args.rule else None)
    except KeyError as e:
        print(f"graftlint: {e.args[0]}", file=sys.stderr)
        return 2

    paths = []
    for p in (args.paths or ["paddle_tpu"]):
        paths.append(p if os.path.exists(p) else os.path.join(REPO_ROOT, p))
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"graftlint: no such path: {', '.join(missing)}",
              file=sys.stderr)
        return 2

    cache = analysis.ModuleCache()
    t0 = time.monotonic()
    findings = analysis.run_paths(paths, rules=rules, root=REPO_ROOT,
                                  cache=cache)
    sweep_seconds = time.monotonic() - t0

    baseline_path = None if args.no_baseline else args.baseline
    baseline = analysis.load_baseline(baseline_path)

    if args.baseline_update:
        new = analysis.Baseline.from_findings(
            findings, default_reason="TODO: justify or fix")
        new.carry_reasons_from(baseline)
        if args.prune_stale:
            for e in baseline.stale_entries(findings):
                print(f"graftlint: pruned stale {e['rule']} "
                      f"{e['path']}:{e.get('line', '?')} "
                      f"[{e['fingerprint']}]")
        else:
            new.adopt_missing_from(baseline)
        new.dump(args.baseline)
        print(f"graftlint: wrote {len(new)} entries to {args.baseline}")
        return 0

    if args.prune_stale:
        if args.no_baseline:
            print("graftlint: --prune-stale needs a baseline "
                  "(--no-baseline given)", file=sys.stderr)
            return 2
        pruned = baseline.prune_stale(findings)
        for e in pruned:
            print(f"graftlint: pruned stale {e['rule']} "
                  f"{e['path']}:{e.get('line', '?')} "
                  f"[{e['fingerprint']}]")
        baseline.dump(args.baseline)
        print(f"graftlint: pruned {len(pruned)} entr"
              f"{'y' if len(pruned) == 1 else 'ies'}, "
              f"{len(baseline)} remain in {args.baseline}")
        return 0

    fresh, known = baseline.split(findings)
    stale = baseline.stale_entries(findings)

    if args.format == "json":
        report = analysis.runner.report_json(
            fresh, baselined=known, stale=stale, errors=cache.errors,
            sweep_seconds=sweep_seconds)
        report["stale_baseline"] = stale
        json.dump(report, sys.stdout, indent=2)
        print()
    elif args.format == "sarif":
        rules_for_table = rules if rules is not None \
            else analysis.all_rules()
        json.dump(analysis.report_sarif(fresh, rules=rules_for_table),
                  sys.stdout, indent=2)
        print()
    else:
        for f in fresh:
            print(f.render())
        for path, err in sorted(cache.errors.items()):
            print(f"{path}: PARSE-ERROR: {err}")
        summary = (f"graftlint: {len(fresh)} unbaselined finding(s), "
                   f"{len(known)} baselined, {len(stale)} stale baseline "
                   f"entr{'y' if len(stale) == 1 else 'ies'}")
        print(summary)
        for e in stale:
            print(f"  stale: {e['rule']} {e['path']}:{e.get('line', '?')} "
                  f"(fixed? delete the entry)")
    return 1 if (fresh or cache.errors) else 0


if __name__ == "__main__":
    sys.exit(main())
