"""paddle.summary (ref: python/paddle/hapi/model_summary.py, upstream layout,
unverified — mount empty). Uses jax.eval_shape — no FLOPs are spent."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..jit.functional import call_functional, extract_state

__all__ = ["summary"]


def summary(net, input_size=None, dtypes=None, input=None):
    """Print a per-layer table; returns {'total_params', 'trainable_params'}."""
    rows = []
    hooks = []

    def make_hook(name):
        def hook(layer, inputs, outputs):
            outs = outputs if isinstance(outputs, (list, tuple)) else \
                [outputs]
            shapes = [list(o.shape) for o in outs if isinstance(o, Tensor)]
            n_params = sum(
                int(np.prod(p.shape)) for p in layer._parameters.values()
                if p is not None)
            rows.append((name, type(layer).__name__, shapes, n_params))
        return hook

    for name, sub in net.named_sublayers():
        if not sub._sub_layers:  # leaves only
            hooks.append(sub.register_forward_post_hook(make_hook(name)))

    try:
        if input is not None:
            args = [input] if isinstance(input, Tensor) else list(input)
            datas = [a._data for a in args]
        else:
            if input_size is None:
                raise ValueError("summary needs input_size or input")
            sizes = [input_size] if isinstance(input_size, tuple) else \
                list(input_size)
            dts = dtypes or ["float32"] * len(sizes)
            if isinstance(dts, str):
                dts = [dts] * len(sizes)
            datas = [jnp.zeros([1 if s is None or s == -1 else s
                                for s in size], dtype=dt)
                     for size, dt in zip(sizes, dts)]
        params, buffers = extract_state(net)
        # run abstractly — hooks fire during tracing, shapes are exact
        jax.eval_shape(
            lambda p, b, *d: call_functional(net, p, b, d,
                                             training=False)[0],
            params, buffers, *datas)
    finally:
        for h in hooks:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)

    w = max([len(r[0]) + len(r[1]) for r in rows] + [30]) + 8
    line = "-" * (w + 40)
    print(line)
    print(f"{'Layer (type)':<{w}}{'Output Shape':<24}{'Param #':>12}")
    print(line)
    for name, typ, shapes, n in rows:
        shape_s = str(shapes[0]) if len(shapes) == 1 else str(shapes)
        print(f"{name + ' (' + typ + ')':<{w}}{shape_s:<24}{n:>12,}")
    print(line)
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}
