"""Ring attention + Ulysses — first-class long-context primitives (sep axis).

Ref: the reference exposes flash-attn kernels, the sep HCG axis, and
batch_isend_irecv ring primitives, with ring/Ulysses loops composed in the
ecosystem (SURVEY §2.3 "Ring attention"); here both are in-core as the prompt
requires.

* ring_flash_attention: inside shard_map over the sep axis each rank holds a
  sequence shard of Q,K,V; KV blocks rotate around the ring via ppermute
  while the online-softmax accumulator (m, l, o) folds in one block per step
  — flash attention's numerics, ICI-bandwidth communication, O(s/n) memory.
* ulysses_attention: all_to_all reshards sequence<->heads so every rank runs
  full-sequence attention on its head slice, then reshards back (the
  DeepSpeed-Ulysses layout swap).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ....core.tensor import Tensor

__all__ = ["ring_flash_attention", "ulysses_attention", "RingFlashAttention"]


def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _axis_size(name):
    fn = getattr(jax.lax, "axis_size", None)        # jax >= 0.5
    if fn is None:
        fn = jax.core.axis_frame                    # jax 0.4.x: returns size
    return int(fn(name))


def ring_flash_attention(q, k, v, group=None, causal: bool = False,
                         axis_name: Optional[str] = None,
                         scale: Optional[float] = None,
                         impl: Optional[str] = None,
                         interpret: bool = False):
    """Ring attention over a sequence-sharded axis.

    Args are [batch, heads, s_local, head_dim] shards inside shard_map over
    `axis_name` (or group.axis_name). Returns the local attention output
    shard. Outside a named axis, falls back to plain attention.

    impl: None (auto: Pallas on TPU, XLA einsum elsewhere) | "pallas" |
    "xla". The Pallas path runs the flash kernel per ring step — bf16 MXU
    matmuls, in-kernel causal offsets, no materialized score block
    (SURVEY §5's "ring attention as a Pallas splash/flash kernel").
    """
    qd, kd, vd = _unwrap(q), _unwrap(k), _unwrap(v)
    name = axis_name or (group.axis_name if group is not None else "sep")
    scale = scale if scale is not None else qd.shape[-1] ** -0.5

    try:
        n = _axis_size(name)
    except (NameError, KeyError, TypeError, ValueError):
        # no live sep axis (eager / outside shard_map) -> local-only.
        # Deliberately NOT broad: an AttributeError from jax API
        # drift in _axis_size must propagate, not silently shrink
        # the ring to the local shard (the PR 5 wrong-result bug).
        n = 1
    if n == 1:
        out = _flash_block(qd, kd, vd, scale, causal, 0, 0, None)
        return Tensor(out.astype(qd.dtype)) if isinstance(q, Tensor) else out

    from ....ops import pallas_kernels as _pk

    use_pallas = impl == "pallas" or (
        impl is None and _pk._on_tpu() and qd.ndim == 4
        and 8 <= qd.shape[-1] <= 256)
    if use_pallas:
        out = _pk.ring_flash_attention_pallas(
            qd, kd, vd, name, causal=causal, scale=scale,
            interpret=interpret)
        return Tensor(out) if isinstance(q, Tensor) else out

    my = jax.lax.axis_index(name)
    s_local = qd.shape[2]

    # online softmax accumulators
    o = jnp.zeros_like(qd, dtype=jnp.float32)
    m = jnp.full(qd.shape[:3], -jnp.inf, dtype=jnp.float32)   # b,h,s
    l = jnp.zeros(qd.shape[:3], dtype=jnp.float32)

    kv = (kd, vd)
    perm = [(i, (i + 1) % n) for i in range(n)]
    for step in range(n):
        src = (my - step) % n     # whose KV block we now hold
        kb, vb = kv
        o, m, l = _online_update(qd, kb, vb, o, m, l, scale, causal,
                                 my, src, s_local)
        if step != n - 1:
            kv = jax.lax.ppermute(kv, name, perm)
    out = (o / l[..., None]).astype(qd.dtype)
    if isinstance(q, Tensor):
        return Tensor(out)
    return out


def _online_update(qd, kb, vb, o, m, l, scale, causal, my_idx, src_idx,
                   s_local):
    """Fold one KV block into the (o, m, l) accumulator (flash attention's
    streaming softmax)."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", qd.astype(jnp.float32),
                        kb.astype(jnp.float32)) * scale
    if causal:
        q_pos = my_idx * s_local + jnp.arange(s_local)[:, None]
        k_pos = src_idx * s_local + jnp.arange(kb.shape[2])[None, :]
        mask = q_pos >= k_pos
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    block_max = jnp.max(scores, axis=-1)
    new_m = jnp.maximum(m, block_max)
    # guard fully-masked rows (new_m = -inf)
    safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
    p = jnp.exp(scores - safe_m[..., None])
    p = jnp.where(jnp.isfinite(scores), p, 0.0)
    correction = jnp.where(jnp.isfinite(m), jnp.exp(m - safe_m), 0.0)
    new_l = l * correction + jnp.sum(p, axis=-1)
    new_o = o * correction[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, vb.astype(jnp.float32))
    return new_o, new_m, new_l


def _flash_block(qd, kd, vd, scale, causal, my, src, _):
    scores = jnp.einsum("bhqd,bhkd->bhqk", qd, kd) * scale
    if causal:
        s_q, s_k = qd.shape[2], kd.shape[2]
        mask = jnp.arange(s_q)[:, None] >= jnp.arange(s_k)[None, :]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, vd)


def ulysses_attention(q, k, v, group=None, causal: bool = False,
                      axis_name: Optional[str] = None,
                      scale: Optional[float] = None,
                      impl: Optional[str] = None,
                      interpret: bool = False):
    """Ulysses: all_to_all seq<->heads, full-sequence attention, reshard back.

    Inputs [b, h, s_local, d] sharded on seq inside shard_map; heads must be
    divisible by the axis size. The full-sequence attention on each head
    slice runs the Pallas flash kernel on TPU (impl="pallas" to force,
    "xla" for the materialized reference).
    """
    qd, kd, vd = _unwrap(q), _unwrap(k), _unwrap(v)
    name = axis_name or (group.axis_name if group is not None else "sep")
    try:
        n = _axis_size(name)
    except (NameError, KeyError, TypeError, ValueError):
        # no live sep axis (eager / outside shard_map) -> local-only.
        # Deliberately NOT broad: an AttributeError from jax API
        # drift in _axis_size must propagate, not silently shrink
        # the ring to the local shard (the PR 5 wrong-result bug).
        n = 1
    scale = scale if scale is not None else qd.shape[-1] ** -0.5
    if n == 1:
        out = _flash_block(qd, kd, vd, scale, causal, 0, 0, None)
        return Tensor(out) if isinstance(q, Tensor) else out

    assert qd.shape[1] % n == 0, "heads must divide the sep axis size"

    def seq_to_heads(x):
        # [b, h, s/n, d] -> all_to_all over heads -> [b, h/n, s, d]
        return jax.lax.all_to_all(x, name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qh, kh, vh = seq_to_heads(qd), seq_to_heads(kd), seq_to_heads(vd)
    from ....ops import pallas_kernels as _pk

    default_scale = abs(scale - qd.shape[-1] ** -0.5) < 1e-12
    if impl == "pallas" and not default_scale:
        raise ValueError(
            "ulysses impl='pallas' supports the default 1/sqrt(d) scale "
            "only; drop the custom scale or use impl='xla'")
    use_pallas = default_scale and (impl == "pallas" or (
        impl is None and _pk._on_tpu() and 8 <= qd.shape[-1] <= 256))
    if use_pallas:
        # full-sequence flash on the head slice: (b,h,s,d) matches the
        # kernel's padded layout directly; vma declared for shard_map
        out = _pk._fwd_flash_for_ulysses(qh, kh, vh, scale, causal, name,
                                         interpret)
    else:
        out = _flash_block(qh, kh, vh, scale, causal, 0, 0, None)
    out = heads_to_seq(out.astype(qd.dtype))
    return Tensor(out) if isinstance(q, Tensor) else out


class RingFlashAttention:
    """Layer-ish wrapper (callable) selecting ring vs ulysses."""

    def __init__(self, mode: str = "ring", group=None, causal: bool = True):
        assert mode in ("ring", "ulysses")
        self.mode = mode
        self.group = group
        self.causal = causal

    def __call__(self, q, k, v):
        fn = (ring_flash_attention if self.mode == "ring"
              else ulysses_attention)
        return fn(q, k, v, group=self.group, causal=self.causal)
