"""graftlint v2 — the project-wide engine and the serving-contract rules.

Covers the PR 14 surface on top of tests/test_lint.py's v1 suite:

  * the five new rules, each with true-positive / suppressed / clean
    fixtures reduced from the shipped bug class they encode;
  * CallGraph unit behavior: import cycles, bounded re-export chase,
    closure call edges (the v1 HOST-SYNC contract), module-alias
    chains, constant resolution through from-imports;
  * the dataflow driver: branch-union merge, bounded loop passes,
    try/except joins, PerTarget unpacking, Summarizer depth/cycle
    bounds;
  * whole-tree properties: two sweeps are byte-identical, the sweep
    fits the < 3 s CPU budget, SARIF output round-trips;
  * baseline ergonomics: --prune-stale alone and with
    --baseline-update.

No jax import anywhere in this file — the analysis package loads
standalone exactly as tools/graftlint.py loads it.
"""
import importlib.util
import json
import os
import sys
import textwrap
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CLI_PATH = os.path.join(REPO, "tools", "graftlint.py")


def _load_cli():
    mod = sys.modules.get("_graftlint_cli")
    if mod is not None:
        return mod
    spec = importlib.util.spec_from_file_location("_graftlint_cli", _CLI_PATH)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_graftlint_cli"] = mod
    spec.loader.exec_module(mod)
    return mod


graftlint = _load_cli()
analysis = graftlint.load_analysis()


def run(source, path="fix.py", rule=None):
    rules = [analysis.get_rule(rule)] if rule else None
    return analysis.run_source(textwrap.dedent(source), path=path,
                               rules=rules)


def project_of(**files):
    """Build a Project from {dotted_name: source} (dots become dirs)."""
    modules = {}
    for dotted, src in files.items():
        path = dotted.replace(".", "/") + ".py"
        modules[path] = analysis.ParsedModule(path, textwrap.dedent(src))
    return analysis.Project(modules=modules)


def write_pkg(root, files):
    """Materialize {relpath: source} under root for run_paths tests."""
    for rel, src in files.items():
        full = os.path.join(root, rel)
        os.makedirs(os.path.dirname(full), exist_ok=True)
        with open(full, "w") as f:
            f.write(textwrap.dedent(src))


# ---------------------------------------------------------------------------
# DONATED-REUSE
# ---------------------------------------------------------------------------
class TestDonatedReuse:
    def test_read_after_donation_fires(self):
        fs = run("""
            import jax
            def step(self, params, pools):
                fn = jax.jit(self._impl, donate_argnums=(1,))
                out = fn(params, pools)
                x = pools.sum()
                return out
        """, rule="DONATED-REUSE")
        assert [f.line for f in fs] == [6]
        assert "donated" in fs[0].message

    def test_rebind_from_output_is_clean(self):
        fs = run("""
            import jax
            def step(self, params, pools):
                fn = jax.jit(self._impl, donate_argnums=(1,))
                out = fn(params, pools)
                pools = out[1]
                return pools.sum()
        """, rule="DONATED-REUSE")
        assert fs == []

    def test_subscript_write_into_donated_fires(self):
        fs = run("""
            import jax
            def step(self, params, pools):
                fn = jax.jit(self._impl, donate_argnums=(1,))
                out = fn(params, pools)
                pools[0] = out[1]
                return out
        """, rule="DONATED-REUSE")
        assert [f.line for f in fs] == [6]
        assert "written into" in fs[0].message

    def test_builder_call_counts_as_donating(self):
        fs = run("""
            import jax
            def _build(fn):
                return jax.jit(fn, donate_argnums=(0,))
            def step(pools, fn):
                f = _build(fn)
                out = f(pools)
                return pools.shape
        """, rule="DONATED-REUSE")
        assert [f.line for f in fs] == [8]

    def test_branch_merge_is_union(self):
        # donated on one branch only -> still donated after the If
        fs = run("""
            import jax
            def step(self, params, pools, fast):
                fn = jax.jit(self._impl, donate_argnums=(1,))
                if fast:
                    out = fn(params, pools)
                else:
                    out = None
                return pools.sum()
        """, rule="DONATED-REUSE")
        assert [f.line for f in fs] == [9]

    def test_noqa_suppresses(self):
        fs = run("""
            import jax
            def step(self, params, pools):
                fn = jax.jit(self._impl, donate_argnums=(1,))
                out = fn(params, pools)
                x = pools.sum()  # noqa: DONATED-REUSE — debug-only read before rebind
                return out
        """, rule="DONATED-REUSE")
        assert fs == []

    def test_cross_module_builder(self, tmp_path):
        write_pkg(str(tmp_path), {
            "pkg/__init__.py": "",
            "pkg/builders.py": """
                import jax
                def make_step(fn):
                    return jax.jit(fn, donate_argnums=(0,))
            """,
            "pkg/caller.py": """
                from pkg.builders import make_step
                def drive(pools, fn):
                    f = make_step(fn)
                    out = f(pools)
                    return pools.shape
            """,
        })
        fs = analysis.run_paths([str(tmp_path)], root=str(tmp_path),
                                rules=[analysis.get_rule("DONATED-REUSE")])
        assert [(f.path, f.line) for f in fs] == [("pkg/caller.py", 6)]


# ---------------------------------------------------------------------------
# KEY-REUSE
# ---------------------------------------------------------------------------
class TestKeyReuse:
    def test_double_consumption_fires(self):
        fs = run("""
            import jax
            def sample(key):
                a = jax.random.normal(key)
                b = jax.random.uniform(key)
                return a + b
        """, rule="KEY-REUSE")
        assert [f.line for f in fs] == [5]
        assert "second" in fs[0].message

    def test_split_then_use_is_clean(self):
        fs = run("""
            import jax
            def sample(key):
                k1, k2 = jax.random.split(key)
                a = jax.random.normal(k1)
                b = jax.random.normal(k2)
                return a + b
        """, rule="KEY-REUSE")
        assert fs == []

    def test_split_targets_are_distinct(self):
        # consuming BOTH halves of one split is the whole point; only a
        # second consumption of the SAME half fires
        fs = run("""
            import jax
            def sample(key):
                k1, k2 = jax.random.split(key)
                a = jax.random.normal(k1)
                b = jax.random.normal(k1)
                return a + b
        """, rule="KEY-REUSE")
        assert [f.line for f in fs] == [6]

    def test_loop_reuse_fires(self):
        fs = run("""
            import jax
            def gen(key, n):
                outs = []
                for i in range(n):
                    outs.append(jax.random.normal(key))
                return outs
        """, rule="KEY-REUSE")
        assert [f.line for f in fs] == [6]
        assert "loop" in fs[0].message

    def test_loop_split_rebind_is_clean(self):
        fs = run("""
            import jax
            def gen(key, n):
                outs = []
                for i in range(n):
                    key, sub = jax.random.split(key)
                    outs.append(jax.random.normal(sub))
                return outs
        """, rule="KEY-REUSE")
        assert fs == []

    def test_fold_in_per_iteration_is_clean(self):
        fs = run("""
            import jax
            def gen(key, n):
                outs = []
                for i in range(n):
                    sub = jax.random.fold_in(key, i)
                    outs.append(jax.random.normal(sub))
                return outs
        """, rule="KEY-REUSE")
        assert fs == []

    def test_interprocedural_consumer(self):
        # helper consumes its parameter; calling it twice with the same
        # key is the same bug as two direct consumptions
        fs = run("""
            import jax
            def helper(k):
                return jax.random.normal(k)
            def outer(key):
                a = helper(key)
                b = helper(key)
                return a + b
        """, rule="KEY-REUSE")
        assert [f.line for f in fs] == [7]
        assert "helper" in fs[0].message

    def test_escape_to_unknown_call_silences(self):
        # a key passed to an unknown non-jax callable escapes: silent
        fs = run("""
            import jax
            def sample(key, sink):
                sink(key)
                a = jax.random.normal(key)
                return a
        """, rule="KEY-REUSE")
        assert fs == []

    def test_noqa_suppresses(self):
        fs = run("""
            import jax
            def sample(key):
                a = jax.random.normal(key)
                b = jax.random.uniform(key)  # noqa: KEY-REUSE — intentional correlated draw
                return a + b
        """, rule="KEY-REUSE")
        assert fs == []


# ---------------------------------------------------------------------------
# COLLECTIVE-MESH
# ---------------------------------------------------------------------------
class TestCollectiveMesh:
    def test_undeclared_axis_fires(self):
        fs = run("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P
            def build(devs, fn):
                mesh = Mesh(devs, axis_names=("dp",))
                return shard_map(lambda x: jax.lax.psum(x, "tp"), mesh=mesh,
                                 in_specs=P(), out_specs=P())
        """, rule="COLLECTIVE-MESH")
        assert [f.line for f in fs] == [7]
        assert "'tp'" in fs[0].message and "['dp']" in fs[0].message

    def test_declared_axis_is_clean(self):
        fs = run("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P
            def build(devs, fn):
                mesh = Mesh(devs, axis_names=("tp",))
                return shard_map(lambda x: jax.lax.psum(x, "tp"), mesh=mesh,
                                 in_specs=P(), out_specs=P())
        """, rule="COLLECTIVE-MESH")
        assert fs == []

    def test_parameter_carried_axis_is_skipped(self):
        # axis arrives as a function parameter: unresolvable, no guess
        fs = run("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P
            def build(devs, fn, axis):
                mesh = Mesh(devs, axis_names=("dp",))
                return shard_map(lambda x: jax.lax.psum(x, axis), mesh=mesh,
                                 in_specs=P(), out_specs=P())
        """, rule="COLLECTIVE-MESH")
        assert fs == []

    def test_constant_chased_through_import(self, tmp_path):
        write_pkg(str(tmp_path), {
            "pkg/__init__.py": "",
            "pkg/consts.py": 'TP_AXIS = "tp"\n',
            "pkg/net.py": """
                import jax
                from jax.experimental.shard_map import shard_map
                from jax.sharding import Mesh, PartitionSpec as P
                from pkg.consts import TP_AXIS
                def build(devs, fn):
                    mesh = Mesh(devs, axis_names=("dp",))
                    return shard_map(lambda x: jax.lax.psum(x, TP_AXIS),
                                     mesh=mesh, in_specs=P(), out_specs=P())
            """,
        })
        fs = analysis.run_paths([str(tmp_path)], root=str(tmp_path),
                                rules=[analysis.get_rule("COLLECTIVE-MESH")])
        assert [(f.path, f.line) for f in fs] == [("pkg/net.py", 8)]

    def test_check_rep_false_without_noqa_fires(self):
        fs = run("""
            import jax
            from jax import shard_map as _sm
            def build(mesh, fn):
                return _sm(fn, mesh=mesh, in_specs=None, out_specs=None,
                           check_rep=False)
        """, rule="COLLECTIVE-MESH")
        assert [f.line for f in fs] == [6]
        assert "no `# noqa`" in fs[0].message

    def test_reasonless_noqa_is_itself_the_finding(self):
        fs = run("""
            import jax
            from jax import shard_map as _sm
            def build(mesh, fn):
                return _sm(fn, mesh=mesh, in_specs=None, out_specs=None,
                           check_rep=False)  # noqa: COLLECTIVE-MESH
        """, rule="COLLECTIVE-MESH")
        assert [f.line for f in fs] == [6]
        assert "reasonless" in fs[0].message

    def test_reasoned_noqa_is_clean(self):
        fs = run("""
            import jax
            from jax import shard_map as _sm
            def build(mesh, fn):
                return _sm(fn, mesh=mesh, in_specs=None, out_specs=None,
                           check_rep=False)  # noqa: COLLECTIVE-MESH — per-shard outputs by contract
        """, rule="COLLECTIVE-MESH")
        assert fs == []

    def test_no_shard_map_no_findings(self):
        # collectives outside shard_map modules are pmap-land: out of scope
        fs = run("""
            import jax
            def allreduce(x):
                return jax.lax.psum(x, "tp")
        """, rule="COLLECTIVE-MESH")
        assert fs == []

    # ---- the ZeRO reduce-scatter / all-gather idiom (ISSUE 16) -------
    # parallel/mesh.py builds its ordered collectives out of
    # jax.lax.all_gather + fixed-order sums; the sharded update in
    # parallel/zero.py gathers updated param slices back with the same
    # primitive. These fixtures pin that the rule sees through the
    # idiom: gathers/scatters on a declared dp axis are clean, a stale
    # axis in either half of the exchange fires.

    def test_allgather_on_declared_dp_axis_is_clean(self):
        fs = run("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P
            DP_AXIS = "dp"
            def ordered_psum(x):
                # all-gather then fixed-shard-order sum: the ordered
                # (bit-deterministic) allreduce idiom
                chunks = jax.lax.all_gather(x, DP_AXIS)
                total = chunks[0]
                for i in range(1, 4):
                    total = total + chunks[i]
                return total
            def build(devs):
                mesh = Mesh(devs, axis_names=("dp", "tp"))
                return shard_map(ordered_psum, mesh=mesh,
                                 in_specs=P("dp"), out_specs=P("dp"))
        """, rule="COLLECTIVE-MESH")
        assert fs == []

    def test_allgather_stale_axis_fires(self):
        # the all-gather half of the exchange against an axis the mesh
        # never declared: wrong values, no error, once check_rep is off
        fs = run("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P
            def gather_params(x):
                return jax.lax.all_gather(x, "sharding")
            def build(devs):
                mesh = Mesh(devs, axis_names=("dp", "tp"))
                return shard_map(gather_params, mesh=mesh,
                                 in_specs=P("dp"), out_specs=P("dp"))
        """, rule="COLLECTIVE-MESH")
        assert [f.line for f in fs] == [6]
        assert "'sharding'" in fs[0].message
        assert "all_gather" in fs[0].message

    def test_psum_scatter_stale_axis_fires(self):
        # the reduce-scatter half: a typo'd module constant resolves and
        # is checked against the declared axes
        fs = run("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P
            GRAD_AXIS = "data"
            def shard_grads(g):
                return jax.lax.psum_scatter(g, GRAD_AXIS)
            def build(devs):
                mesh = Mesh(devs, axis_names=("dp", "tp"))
                return shard_map(shard_grads, mesh=mesh,
                                 in_specs=P("dp"), out_specs=P("dp"))
        """, rule="COLLECTIVE-MESH")
        assert [f.line for f in fs] == [7]
        assert "'data'" in fs[0].message

    def test_parallel_mesh_axis_constants_chase(self, tmp_path):
        # the substrate layout itself: DP_AXIS/TP_AXIS live in one
        # module, the ZeRO step imports them — constants chase through
        # the from-import and both halves of the exchange stay clean
        write_pkg(str(tmp_path), {
            "pkg/__init__.py": "",
            "pkg/mesh.py": 'DP_AXIS = "dp"\nTP_AXIS = "tp"\n',
            "pkg/zero.py": """
                import jax
                from jax.experimental.shard_map import shard_map
                from jax.sharding import Mesh, PartitionSpec as P
                from pkg.mesh import DP_AXIS, TP_AXIS
                def step(g):
                    mine = jax.lax.psum_scatter(g, DP_AXIS)
                    return jax.lax.all_gather(mine, DP_AXIS)
                def build(devs):
                    mesh = Mesh(devs, axis_names=("dp", "tp"))
                    return shard_map(step, mesh=mesh, in_specs=P("dp"),
                                     out_specs=P("dp"))
            """,
        })
        fs = analysis.run_paths([str(tmp_path)], root=str(tmp_path),
                                rules=[analysis.get_rule("COLLECTIVE-MESH")])
        assert fs == []

    # ---- the split-collective ppermute ring idiom (ISSUE 18) ---------
    # serving/overlap.py moves psum payloads over a fixed-order
    # ppermute ring so the reduction can interleave with consumer
    # matmuls. The ring's permutation table must be built from the
    # declared mesh axis size: a table literal-coded for one tp degree
    # silently drops shards at any other.

    def test_ppermute_literal_table_fires(self):
        fs = run("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P
            def rotate(x):
                return jax.lax.ppermute(x, "tp", perm=[(0, 1), (1, 0)])
            def build(devs):
                mesh = Mesh(devs, axis_names=("tp",))
                return shard_map(rotate, mesh=mesh, in_specs=P("tp"),
                                 out_specs=P("tp"))
        """, rule="COLLECTIVE-MESH")
        assert [f.line for f in fs] == [6]
        assert "literal" in fs[0].message
        assert "ring_perm" in fs[0].message

    def test_ppermute_range_literal_comprehension_fires(self):
        # a comprehension over range(2) pins the shard count at write
        # time just as hard as the expanded table does
        fs = run("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P
            def rotate(x):
                return jax.lax.ppermute(
                    x, "tp", perm=[(s, (s + 1) % 2) for s in range(2)])
            def build(devs):
                mesh = Mesh(devs, axis_names=("tp",))
                return shard_map(rotate, mesh=mesh, in_specs=P("tp"),
                                 out_specs=P("tp"))
        """, rule="COLLECTIVE-MESH")
        assert [f.line for f in fs] == [6]
        assert "literal" in fs[0].message

    def test_ppermute_mesh_sized_table_is_clean(self):
        # the blessed idiom: the table comes from a helper fed the
        # declared axis size — nothing literal, nothing to pin
        fs = run("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P
            def ring_perm(n):
                return [(s, (s + 1) % n) for s in range(n)]
            def make_rotate(axis_size):
                perm = ring_perm(axis_size)
                def rotate(x):
                    return jax.lax.ppermute(x, "tp", perm=perm)
                return rotate
            def build(devs, axis_size):
                mesh = Mesh(devs, axis_names=("tp",))
                return shard_map(make_rotate(axis_size), mesh=mesh,
                                 in_specs=P("tp"), out_specs=P("tp"))
        """, rule="COLLECTIVE-MESH")
        assert fs == []

    def test_ppermute_stale_axis_still_fires(self):
        # the ring check composes with the axis check: a mesh-sized
        # table does not excuse naming an axis the mesh never declared
        fs = run("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P
            def make_rotate(perm):
                def rotate(x):
                    return jax.lax.ppermute(x, "ring", perm=perm)
                return rotate
            def build(devs, perm):
                mesh = Mesh(devs, axis_names=("tp",))
                return shard_map(make_rotate(perm), mesh=mesh,
                                 in_specs=P("tp"), out_specs=P("tp"))
        """, rule="COLLECTIVE-MESH")
        assert [f.line for f in fs] == [7]
        assert "'ring'" in fs[0].message
        assert "ppermute" in fs[0].message

    def test_ppermute_literal_fires_without_mesh_resolution(self):
        # the literal-table hazard needs no mesh: even when no Mesh
        # constructor resolves (mesh arrives as a parameter), the ring
        # check still runs
        fs = run("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import PartitionSpec as P
            def rotate(x):
                return jax.lax.ppermute(x, "tp", perm=[(0, 1), (1, 0)])
            def build(mesh):
                return shard_map(rotate, mesh=mesh, in_specs=P("tp"),
                                 out_specs=P("tp"))
        """, rule="COLLECTIVE-MESH")
        assert [f.line for f in fs] == [6]
        assert "ring_perm" in fs[0].message

    # ---- training-side ring (ISSUE 20) -------------------------------
    # parallel/zero.py now moves grad BUCKETS over the same ppermute
    # ring on the dp axis (ring-pipelined reduce-scatter). The contract
    # is axis-agnostic: a perm table literal-coded for one dp degree
    # drops grad shards at any other, which silently corrupts the
    # optimizer update instead of crashing.

    def test_training_dp_ring_literal_table_fires(self):
        fs = run("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P
            def reduce_scatter_bucket(flat):
                moved = flat
                for _ in range(3):
                    moved = jax.lax.ppermute(
                        moved, "dp", perm=[(0, 1), (1, 2), (2, 3), (3, 0)])
                return moved
            def build(devs):
                mesh = Mesh(devs, axis_names=("dp",))
                return shard_map(reduce_scatter_bucket, mesh=mesh,
                                 in_specs=P("dp"), out_specs=P("dp"))
        """, rule="COLLECTIVE-MESH")
        assert [f.line for f in fs] == [8]
        assert "literal" in fs[0].message

    def test_training_dp_ring_mesh_sized_table_is_clean(self):
        # the engine's actual idiom: ring_perm(dp) built once from the
        # declared axis size, closed over by the hop body
        fs = run("""
            import jax
            from jax.experimental.shard_map import shard_map
            from jax.sharding import Mesh, PartitionSpec as P
            def ring_perm(n):
                return [(s, (s + 1) % n) for s in range(n)]
            def make_bucket_scatter(dp):
                perm = ring_perm(dp)
                def scatter(flat):
                    moved = flat
                    for _ in range(dp - 1):
                        moved = jax.lax.ppermute(moved, "dp", perm=perm)
                    return moved
                return scatter
            def build(devs, dp):
                mesh = Mesh(devs, axis_names=("dp",))
                return shard_map(make_bucket_scatter(dp), mesh=mesh,
                                 in_specs=P("dp"), out_specs=P("dp"))
        """, rule="COLLECTIVE-MESH")
        assert fs == []


# ---------------------------------------------------------------------------
# METRIC-CARDINALITY
# ---------------------------------------------------------------------------
class TestMetricCardinality:
    def test_request_id_label_fires(self):
        fs = run("""
            def emit(reg, request_id):
                reg.counter("reqs", labels={"rid": request_id})
        """, rule="METRIC-CARDINALITY")
        assert [f.line for f in fs] == [3]

    def test_range_loop_label_fires(self):
        fs = run("""
            def emit(reg, n):
                for i in range(n):
                    reg.counter("x", labels={"shard": str(i)})
        """, rule="METRIC-CARDINALITY")
        assert [f.line for f in fs] == [4]

    def test_fstring_label_fires(self):
        fs = run("""
            def emit(reg, host):
                reg.counter("x", labels={"node": f"host-{host}"})
        """, rule="METRIC-CARDINALITY")
        assert [f.line for f in fs] == [3]

    def test_dict_through_variable_fires(self):
        fs = run("""
            def emit(reg, n):
                for i in range(n):
                    d = {"shard": str(i)}
                    reg.counter("x", labels=d)
        """, rule="METRIC-CARDINALITY")
        assert [f.line for f in fs] == [5]

    def test_bounded_iteration_is_clean(self):
        # iterating a finite collection (the slo.py classes idiom) is
        # exactly the bounded-enum pattern the rule must not flag
        fs = run("""
            def emit(reg, classes):
                for cls in classes:
                    reg.counter("x", labels={"cls": cls})
        """, rule="METRIC-CARDINALITY")
        assert fs == []

    def test_constant_labels_are_clean(self):
        fs = run("""
            def emit(reg):
                reg.counter("x", labels={"phase": "prefill"})
        """, rule="METRIC-CARDINALITY")
        assert fs == []

    def test_noqa_suppresses(self):
        fs = run("""
            def emit(reg, n):
                for i in range(n):
                    reg.counter("x", labels={"shard": str(i)})  # noqa: METRIC-CARDINALITY — n is tp_size, fixed at boot
        """, rule="METRIC-CARDINALITY")
        assert fs == []


# ---------------------------------------------------------------------------
# STATE-REVERT
# ---------------------------------------------------------------------------
class TestStateRevert:
    def test_charge_without_revert_fires(self):
        fs = run("""
            class Sched:
                def step(self, req):
                    req.num_computed_tokens += 16
                    out = self.model._guarded_call(req)
                    return out
        """, rule="STATE-REVERT")
        assert [f.line for f in fs] == [4]

    def test_revert_on_none_is_clean(self):
        fs = run("""
            class Sched:
                def step(self, req):
                    req.num_computed_tokens += 16
                    out = self.model._guarded_call(req)
                    if out is None:
                        req.num_computed_tokens -= 16
                        return None
                    return out
        """, rule="STATE-REVERT")
        assert fs == []

    def test_revert_in_except_is_clean(self):
        fs = run("""
            class Sched:
                def step(self, req):
                    req.num_computed_tokens += 16
                    try:
                        out = self.model._guarded_call(req)
                    except Exception:
                        req.num_computed_tokens -= 16
                        raise
                    return out
        """, rule="STATE-REVERT")
        assert fs == []

    def test_charge_after_guard_is_clean(self):
        # charging only on success needs no revert
        fs = run("""
            class Sched:
                def step(self, req):
                    out = self.model._guarded_call(req)
                    req.num_computed_tokens += 16
                    return out
        """, rule="STATE-REVERT")
        assert fs == []

    def test_non_accounting_attr_is_clean(self):
        fs = run("""
            class Sched:
                def step(self, req):
                    req.last_step = "decode"
                    out = self.model._guarded_call(req)
                    return out
        """, rule="STATE-REVERT")
        assert fs == []

    def test_noqa_suppresses(self):
        fs = run("""
            class Sched:
                def step(self, req):
                    req.num_computed_tokens += 16  # noqa: STATE-REVERT — caller reverts via restore()
                    out = self.model._guarded_call(req)
                    return out
        """, rule="STATE-REVERT")
        assert fs == []

    def test_spec_charge_revert_idiom_is_clean(self):
        # ISSUE 17: the speculative block's idiom — the worst-case
        # in-flight charge lands only AFTER the guarded dispatch
        # succeeds, and the drain's failure branch reverts it — the
        # exact shape engine._spec_decode/_drain_record ship
        fs = run("""
            class Engine:
                def spec_block(self, reqs, incr):
                    out = self._guarded_call(self.dispatch)
                    if out is None:
                        return []
                    for req, n in zip(reqs, incr):
                        req.inflight += n
                    return out

                def drain(self, rec):
                    toks = self._guarded_call(self.pull)
                    if toks is None:
                        for i, req in enumerate(rec["reqs"]):
                            req.inflight = max(
                                req.inflight - rec["incr"][i], 0)
                        return []
                    return toks
        """, rule="STATE-REVERT")
        assert fs == []

    def test_spec_charge_before_dispatch_fires(self):
        # the dirty variant: charging the speculative worst case BEFORE
        # the dispatch with no revert — a quarantined fault would leave
        # pages reserved for horizon*(1+lookahead) tokens that never ran
        fs = run("""
            class Engine:
                def spec_block(self, reqs, cap_tokens):
                    for req in reqs:
                        req.inflight += cap_tokens
                    out = self._guarded_call(self.dispatch)
                    return out
        """, rule="STATE-REVERT")
        assert [f.line for f in fs] == [5]


# ---------------------------------------------------------------------------
# CallGraph
# ---------------------------------------------------------------------------
class TestCallGraph:
    def test_import_cycle_terminates(self):
        project = project_of(**{
            "pkg.a": """
                from pkg.b import g
                def f():
                    return g()
            """,
            "pkg.b": """
                from pkg.a import f
                def g():
                    return f()
            """,
        })
        graph = project.callgraph
        fa = graph.resolve_symbol("pkg/a.py", "g")
        fb = graph.resolve_symbol("pkg/b.py", "f")
        assert [fn.name for fn in fa] == ["g"]
        assert [fn.name for fn in fb] == ["f"]

    def test_reexport_chase_is_bounded(self):
        # a -> b -> c -> d -> e re-export chain exceeds _MAX_CHASE and
        # resolves to nothing rather than recursing forever
        files = {}
        for i, (src, dst) in enumerate(
                [("a", "b"), ("b", "c"), ("c", "d"), ("d", "e"),
                 ("e", "f")]):
            files[f"pkg.{src}"] = f"from pkg.{dst} import target\n"
        files["pkg.f"] = "def target():\n    pass\n"
        project = project_of(**files)
        hit = project.callgraph.resolve_symbol("pkg/f.py", "target")
        assert [fn.name for fn in hit] == ["target"]
        assert project.callgraph.resolve_symbol("pkg/a.py", "target") == []

    def test_closure_calls_belong_to_the_outer_function(self):
        # the v1 HOST-SYNC contract: a closure's calls are reachable
        # from the function that defines (and runs) it
        project = project_of(**{
            "pkg.m": """
                class Engine:
                    def outer(self):
                        def inner():
                            return self.helper()
                        return inner()
                    def helper(self):
                        return 1
                    def cold(self):
                        return 2
            """,
        })
        names = project.callgraph.reachable_names("pkg/m.py", {"outer"})
        assert "helper" in names and "outer" in names
        assert "cold" not in names

    def test_lambda_bodies_contribute_call_edges(self):
        project = project_of(**{
            "pkg.m": """
                def outer():
                    thunk = lambda: helper()
                    return thunk()
                def helper():
                    return 1
            """,
        })
        names = project.callgraph.reachable_names("pkg/m.py", {"outer"})
        assert "helper" in names

    def test_module_alias_chain_resolution(self):
        project = project_of(**{
            "pkg.util": """
                def helper():
                    pass
            """,
            "pkg.m": """
                import pkg.util as u
                def f():
                    return u.helper()
            """,
        })
        hit = project.callgraph.resolve_chain("pkg/m.py", ["u", "helper"])
        assert [fn.key.path for fn in hit] == ["pkg/util.py"]

    def test_resolve_constant_through_from_import(self):
        project = project_of(**{
            "pkg.consts": 'AXIS = "tp"\n',
            "pkg.m": "from pkg.consts import AXIS\n",
        })
        assert project.callgraph.resolve_constant("pkg/m.py", "AXIS") == "tp"

    def test_callees_cross_module(self):
        project = project_of(**{
            "pkg.util": """
                def helper():
                    pass
            """,
            "pkg.m": """
                from pkg.util import helper
                def f():
                    return helper()
            """,
        })
        graph = project.callgraph
        (f,) = graph.by_name("pkg/m.py")["f"]
        callees = graph.callees(f.key)
        assert {k.qualname for k in callees} == {"helper"}
        assert graph.callees(f.key, same_module_only=True) == frozenset()


# ---------------------------------------------------------------------------
# Dataflow driver
# ---------------------------------------------------------------------------
def _flow_env(source, flow_cls=None, **flow_kwargs):
    import ast as _ast
    module = analysis.ParsedModule("flow.py", textwrap.dedent(source))
    cls = flow_cls or analysis.FunctionDataflow
    flow = cls(module, analysis.Project.single(module), **flow_kwargs)
    fns = [n for n in _ast.walk(module.tree)
           if isinstance(n, (_ast.FunctionDef, _ast.AsyncFunctionDef))]
    return flow, flow.run(fns[0])


class _TokenFlow(analysis.FunctionDataflow):
    """make() returns a fresh line-tagged token; everything else opaque."""

    def call_result(self, call, chain, func_value, arg_values,
                    kw_values, env):
        if chain == ["make"]:
            return frozenset({("t", call.lineno)})
        if chain == ["split"]:
            return analysis.PerTarget(
                lambda i: frozenset({("s", call.lineno, i)}))
        return None


class TestDataflow:
    def test_branch_merge_is_union(self):
        _, env = _flow_env("""
            def f(c):
                if c:
                    x = make()
                else:
                    x = make()
                y = x
        """, _TokenFlow)
        assert env["y"] == frozenset({("t", 4), ("t", 6)})

    def test_loop_carried_binding_is_seen(self):
        # pass 1 binds x inside the loop; pass 2 must see it in `y = x`
        _, env = _flow_env("""
            def f(it):
                y = None
                for i in it:
                    y = x if i else make()
                    x = make()
        """, _TokenFlow)
        assert ("t", 6) in env["y"]

    def test_try_handler_joins_pre_and_post_body(self):
        _, env = _flow_env("""
            def f():
                x = make()
                try:
                    x = make()
                except Exception:
                    y = x
                return y
        """, _TokenFlow)
        # the handler may run before OR after the body assignment
        assert env["y"] == frozenset({("t", 3), ("t", 5)})

    def test_per_target_unpack_is_distinct(self):
        _, env = _flow_env("""
            def f():
                a, b = split()
        """, _TokenFlow)
        assert env["a"] == frozenset({("s", 3, 0)})
        assert env["b"] == frozenset({("s", 3, 1)})
        assert env["a"] != env["b"]

    def test_rebinding_base_drops_extensions(self):
        _, env = _flow_env("""
            def f():
                x = make()
                x.sub = make()
                x = make()
        """, _TokenFlow)
        assert "x.sub" not in env
        assert env["x"] == frozenset({("t", 5)})

    def test_summarizer_depth_bound(self):
        calls = []

        def compute(key, depth):
            calls.append((key, depth))
            return summ.get(key + 1, depth + 1)

        summ = analysis.Summarizer(compute, default="BOUND", max_depth=3)
        assert summ.get(0) == "BOUND"
        assert max(d for _, d in calls) == 3

    def test_summarizer_cycle_returns_default(self):
        def compute(key, depth):
            return summ.get(key, depth)  # re-enters itself

        summ = analysis.Summarizer(compute, default="CYCLE")
        assert summ.get("k") == "CYCLE"

    def test_summarizer_memoizes(self):
        count = [0]

        def compute(key, depth):
            count[0] += 1
            return key * 2

        summ = analysis.Summarizer(compute, default=None)
        assert summ.get(21) == 42
        assert summ.get(21) == 42
        assert count[0] == 1


# ---------------------------------------------------------------------------
# Whole-tree properties
# ---------------------------------------------------------------------------
class TestTreeProperties:
    def _sweep(self):
        return analysis.run_paths(
            [os.path.join(REPO, "paddle_tpu")], root=REPO)

    def test_sweep_is_deterministic(self):
        a = [(f.rule, f.path, f.line, f.occurrence, f.fingerprint)
             for f in self._sweep()]
        b = [(f.rule, f.path, f.line, f.occurrence, f.fingerprint)
             for f in self._sweep()]
        assert a == b and a  # identical, and non-trivially so

    def test_sweep_fits_cpu_budget(self):
        # the budget bounds the analyzer's CPU work, not machine load or
        # the GC debt of 1500 earlier tests: collect first, measure CPU
        # seconds, take the best of two so one noisy sample can't flake
        # the gate
        import gc
        gc.collect()
        elapsed = []
        for _ in range(2):
            t0 = time.process_time()
            self._sweep()
            elapsed.append(time.process_time() - t0)
        assert min(elapsed) < 3.0, (
            f"full graftlint sweep took {min(elapsed):.2f}s CPU — the "
            f"tier-1 gate budget is < 3s on CPU")

    def test_sarif_round_trips(self):
        findings = self._sweep()
        rules = analysis.all_rules()
        doc = json.loads(json.dumps(
            analysis.report_sarif(findings, rules=rules)))
        assert doc["version"] == "2.1.0"
        rundoc = doc["runs"][0]
        rule_ids = [r["id"] for r in rundoc["tool"]["driver"]["rules"]]
        assert rule_ids == [r.name for r in rules]
        assert len(rundoc["results"]) == len(findings)
        for res, f in zip(rundoc["results"], findings):
            assert res["ruleId"] == f.rule
            assert rule_ids[res["ruleIndex"]] == f.rule
            loc = res["locations"][0]["physicalLocation"]
            assert loc["artifactLocation"]["uri"] == f.path
            assert loc["region"]["startLine"] == f.line
            assert (res["partialFingerprints"]["graftlint/v1"]
                    == f.fingerprint)


# ---------------------------------------------------------------------------
# Baseline pruning (CLI)
# ---------------------------------------------------------------------------
def _baseline_doc(entries):
    return {"version": 1, "entries": entries}


def _stale_entry():
    return {
        "rule": "SWALLOWED-API", "path": "gone.py", "line": 1,
        "snippet": "pass", "fingerprint": "feedfacefeedface",
        "reason": "code was deleted",
    }


class TestPruneStale:
    def _target(self, tmp_path):
        # a file with one real finding, so the baseline has a live entry
        src = textwrap.dedent("""
            import jax
            def f(x):
                try:
                    return jax.jit(x)()
                except Exception:
                    return None
        """)
        # the CLI resolves every finding path against REPO_ROOT, so the
        # fixture fingerprint must be computed against the same root
        target = tmp_path / "mod.py"
        target.write_text(src)
        fs = analysis.run_paths([str(target)], root=REPO)
        assert fs, "fixture must produce at least one finding"
        live = {
            "rule": fs[0].rule, "path": fs[0].path, "line": fs[0].line,
            "snippet": fs[0].snippet, "fingerprint": fs[0].fingerprint,
            "reason": "intentional fallback",
        }
        return target, live

    def test_prune_stale_rewrites_in_place(self, tmp_path, capsys):
        target, live = self._target(tmp_path)
        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps(_baseline_doc([live, _stale_entry()])))
        rc = graftlint.main([str(target), "--baseline", str(bl),
                             "--prune-stale"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "pruned stale SWALLOWED-API gone.py:1" in out
        doc = json.loads(bl.read_text())
        assert [e["fingerprint"] for e in doc["entries"]] \
            == [live["fingerprint"]]

    def test_baseline_update_preserves_stale_by_default(self, tmp_path):
        target, live = self._target(tmp_path)
        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps(_baseline_doc([live, _stale_entry()])))
        rc = graftlint.main([str(target), "--baseline", str(bl),
                             "--baseline-update"])
        assert rc == 0
        fps = {e["fingerprint"]
               for e in json.loads(bl.read_text())["entries"]}
        assert fps == {live["fingerprint"], "feedfacefeedface"}

    def test_baseline_update_with_prune_drops_stale(self, tmp_path,
                                                    capsys):
        target, live = self._target(tmp_path)
        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps(_baseline_doc([live, _stale_entry()])))
        rc = graftlint.main([str(target), "--baseline", str(bl),
                             "--baseline-update", "--prune-stale"])
        assert rc == 0
        assert "pruned stale" in capsys.readouterr().out
        entries = json.loads(bl.read_text())["entries"]
        assert [e["fingerprint"] for e in entries] \
            == [live["fingerprint"]]
        # the surviving entry keeps its human reason
        assert entries[0]["reason"] == "intentional fallback"

    def test_prune_stale_without_baseline_is_usage_error(self, tmp_path):
        target, _ = self._target(tmp_path)
        rc = graftlint.main([str(target), "--no-baseline",
                             "--prune-stale"])
        assert rc == 2


# ---------------------------------------------------------------------------
# Loader contract
# ---------------------------------------------------------------------------
class TestLoader:
    def test_no_jax_in_analysis_modules(self):
        # the analysis package never imports jax. Standalone, the loader
        # binds it as _graftlint_analysis; under the full pytest suite
        # (conftest imports jax) load_analysis() legitimately reuses the
        # real paddle_tpu.analysis — either way, no module of whichever
        # package we got may have bound a `jax` name
        pkg = analysis.__name__
        for name, mod in list(sys.modules.items()):
            if mod is None:
                continue
            if name == pkg or name.startswith(pkg + "."):
                assert getattr(mod, "jax", None) is None, (
                    f"{name} imported jax")

    def test_v2_symbols_are_exported(self):
        for sym in ("CallGraph", "FuncKey", "FuncNode", "Project",
                    "FunctionDataflow", "PerTarget", "Summarizer",
                    "report_sarif"):
            assert hasattr(analysis, sym), sym
