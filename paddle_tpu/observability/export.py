"""Exporters over a MetricsRegistry: Prometheus text exposition + JSON
snapshot round-trip.

Prometheus format follows the text exposition rules (one `# TYPE` /
optional `# HELP` per metric name, histogram as cumulative `_bucket{le=}`
series plus `_sum`/`_count`) so the output scrapes with a stock
Prometheus server; `registry_from_snapshot` is the inverse of
`MetricsRegistry.snapshot()` — bench JSON files embed snapshots and a
later analysis step can rebuild live histograms from them.
"""
from __future__ import annotations

import math
from typing import Dict

from .metrics import Counter, Gauge, Histogram, MetricsRegistry

__all__ = ["to_prometheus", "registry_from_snapshot"]


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    v = float(v)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    return format(v, ".10g")


def _escape(s: str) -> str:
    """Label-VALUE escaping per the Prometheus text format: backslash
    first (so later substitutions don't double-escape), then newline,
    then double-quote — exactly these three, in exactly this order
    (ISSUE 19 audit; round-tripped in tests/test_observability.py)."""
    return (s.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _escape_help(s: str) -> str:
    """HELP-line escaping: the text format escapes ONLY backslash and
    newline there — HELP text is not quoted, so a literal `"` must
    pass through unescaped (the ISSUE 19 audit's one real gap: HELP
    previously went through the label-value escaper and emitted `\\"`,
    which scrapers render verbatim)."""
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(str(v))}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def to_prometheus(registry: MetricsRegistry) -> str:
    """Text exposition of every metric in the registry."""
    lines = []
    seen = set()
    for m in registry.collect():
        if m.name not in seen:
            seen.add(m.name)
            if m.help:
                lines.append(f"# HELP {m.name} "
                             f"{_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
        if m.kind == "histogram":
            cum = 0
            for i, c in enumerate(m._counts):
                cum += c
                le = m.bucket_upper_bound(i)
                labels = dict(m.labels)
                labels["le"] = _fmt_value(le)
                lines.append(f"{m.name}_bucket{_fmt_labels(labels)} {cum}")
            lines.append(f"{m.name}_sum{_fmt_labels(m.labels)} "
                         f"{_fmt_value(m.sum)}")
            lines.append(f"{m.name}_count{_fmt_labels(m.labels)} "
                         f"{m.count}")
        else:
            lines.append(f"{m.name}{_fmt_labels(m.labels)} "
                         f"{_fmt_value(m.value)}")
    return "\n".join(lines) + "\n"


def registry_from_snapshot(snap: Dict[str, object]) -> MetricsRegistry:
    """Rebuild a registry from `MetricsRegistry.snapshot()` output (or
    its json.dumps/loads round-trip): the rebuilt registry's snapshot
    equals the input."""
    reg = MetricsRegistry()
    for d in snap["metrics"]:
        labels = dict(d.get("labels") or {}) or None
        help_ = d.get("help", "")
        kind = d["type"]
        if kind == "counter":
            reg.counter(d["name"], help_, labels)._value = d["value"]
        elif kind == "gauge":
            reg.gauge(d["name"], help_, labels)._value = d["value"]
        elif kind == "histogram":
            h = reg.histogram(d["name"], help_, labels, lo=d["lo"],
                              hi=d["hi"], growth=d["growth"])
            h._count = d["count"]
            h._sum = d["sum"]
            h._min = d["min"] if d["min"] is not None else math.inf
            h._max = d["max"] if d["max"] is not None else -math.inf
            for k, c in (d.get("buckets") or {}).items():
                h._counts[int(k)] = c
        else:
            raise ValueError(f"unknown metric type {kind!r}")
    return reg
