"""Elementwise & binary math ops (PHI math kernel analog; ref:
paddle/phi/kernels/*, upstream layout, unverified — mount empty).

All functions are pure over jax arrays; broadcasting follows numpy. XLA fuses
chains of these into single kernels, so there is no hand-fusion here.
"""
from __future__ import annotations

import jax.numpy as jnp



def scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jnp.log(x / (1.0 - x))


