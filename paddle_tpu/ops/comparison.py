"""Comparison / logical / bitwise ops."""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


@register_op("equal")
def equal(x, y):
    return jnp.equal(x, y)


@register_op("not_equal")
def not_equal(x, y):
    return jnp.not_equal(x, y)


@register_op("less_than")
def less_than(x, y):
    return jnp.less(x, y)


@register_op("less_equal")
def less_equal(x, y):
    return jnp.less_equal(x, y)


@register_op("greater_than")
def greater_than(x, y):
    return jnp.greater(x, y)


@register_op("greater_equal")
def greater_equal(x, y):
    return jnp.greater_equal(x, y)


@register_op("equal_all")
def equal_all(x, y):
    return jnp.array_equal(x, y)


@register_op("isclose")
def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register_op("allclose")
def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


@register_op("isnan")
def isnan(x):
    return jnp.isnan(x)


@register_op("isinf")
def isinf(x):
    return jnp.isinf(x)


@register_op("isfinite")
def isfinite(x):
    return jnp.isfinite(x)


@register_op("logical_and")
def logical_and(x, y):
    return jnp.logical_and(x, y)


@register_op("logical_or")
def logical_or(x, y):
    return jnp.logical_or(x, y)


@register_op("logical_xor")
def logical_xor(x, y):
    return jnp.logical_xor(x, y)


@register_op("logical_not")
def logical_not(x):
    return jnp.logical_not(x)


@register_op("bitwise_and")
def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


@register_op("bitwise_or")
def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


@register_op("bitwise_xor")
def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


@register_op("bitwise_not")
def bitwise_not(x):
    return jnp.bitwise_not(x)


@register_op("bitwise_left_shift")
def bitwise_left_shift(x, y):
    return jnp.left_shift(x, y)


@register_op("bitwise_right_shift")
def bitwise_right_shift(x, y):
    return jnp.right_shift(x, y)
