"""paddle.distributed.sharding — group_sharded_parallel entry point.

Ref: python/paddle/distributed/sharding/group_sharded.py (upstream layout,
unverified — mount empty).
"""
from .fleet.meta_parallel.sharding import (  # noqa: F401
    GroupShardedOptimizerStage2, GroupShardedStage2, GroupShardedStage3,
    group_sharded_parallel,
)
from ..framework.io import save as _save

__all__ = ["group_sharded_parallel", "save_group_sharded_model"]


def save_group_sharded_model(model, output, optimizer=None):
    """Gather-on-rank0 save (ref: group_sharded.py save util)."""
    if hasattr(model, "get_all_parameters"):
        model.get_all_parameters()
    _save(model.state_dict(), str(output) + ".pdparams")
    if optimizer is not None:
        inner = getattr(optimizer, "_optim", optimizer)
        _save(inner.state_dict(), str(output) + ".pdopt")
