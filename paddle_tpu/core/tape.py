"""Imperative autograd: a tape of vjp closures.

Paddle's eager engine records one GradNode per traced op and runs a
reverse-topological backward (ref: paddle/fluid/eager/backward.cc, upstream
layout, unverified — mount empty). Here each eager op that touches a
grad-requiring tensor is executed through `jax.vjp`, and the returned vjp
closure (holding XLA-resident residuals) becomes the GradNode. `backward()`
walks producers in reverse topological order, accumulating cotangents.

Hot-path note: this tape exists for dygraph parity and debugging; performance
work happens in jitted step functions (hapi/jit/distributed), where autodiff is
jax.grad over the functional model and no tape is involved.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax.numpy as jnp


class GradNode:
    """One recorded op: vjp closure + graph edges."""

    __slots__ = ("vjp_fn", "inputs", "n_outputs", "out_grads", "out_avals",
                 "name", "__weakref__")

    def __init__(self, vjp_fn, inputs, n_outputs: int, name: str = "",
                 out_avals=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs              # list[Tensor] — differentiable positions
        self.n_outputs = n_outputs
        self.out_grads: Optional[list] = None  # cotangent accumulation slots
        self.out_avals = out_avals        # (shape, dtype) per output, for zero-fill
        self.name = name

    def ready(self) -> bool:
        return self.out_grads is not None and all(
            g is not None for g in self.out_grads
        )


class _TapeState:
    enabled = True
    # nesting depth of no_grad contexts
    _guard_depth = 0


_STATE = _TapeState()


def grad_enabled() -> bool:
    return _STATE.enabled


class no_grad:
    """Context manager / decorator disabling tape recording (paddle.no_grad)."""

    def __enter__(self):
        self._prev = _STATE.enabled
        _STATE.enabled = False
        return self

    def __exit__(self, *exc):
        _STATE.enabled = self._prev
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = _STATE.enabled
        _STATE.enabled = True
        return self

    def __exit__(self, *exc):
        _STATE.enabled = self._prev
        return False


def set_grad_enabled(mode: bool):
    class _Guard:
        def __enter__(self_g):
            self_g._prev = _STATE.enabled
            _STATE.enabled = bool(mode)
            return self_g

        def __exit__(self_g, *exc):
            _STATE.enabled = self_g._prev
            return False

    return _Guard()


def _toposort(root_nodes) -> List[GradNode]:
    """Reverse-topological order (consumers before producers) over the
    subgraph reachable from `root_nodes` via node.inputs[*].grad node edges."""
    visited = set()
    order: List[GradNode] = []

    # iterative DFS postorder
    for root in root_nodes:
        if id(root) in visited:
            continue
        stack = [(root, iter(root.inputs))]
        visited.add(id(root))
        while stack:
            node, it = stack[-1]
            advanced = False
            for t in it:
                prod = t._grad_node
                if prod is not None and id(prod) not in visited:
                    visited.add(id(prod))
                    stack.append((prod, iter(prod.inputs)))
                    advanced = True
                    break
            if not advanced:
                order.append(node)
                stack.pop()
    order.reverse()  # consumers first
    return order


def backward(tensors, grad_tensors=None, retain_graph: bool = False,
             targets=None, store=None, accumulate_leaf: bool = True):
    """Run the backward engine from `tensors` (paddle.autograd.backward).

    `targets`/`store` support paddle.grad(): cotangents deposited for tensors
    whose id is in `targets` are also accumulated into `store[id]`.
    """
    from .tensor import Tensor

    def _collect(t, g):
        if targets is not None and id(t) in targets:
            store[id(t)] = g if id(t) not in store else store[id(t)] + g

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    # Seed cotangents.
    roots = []
    for t, g in zip(tensors, grad_tensors):
        node = t._grad_node
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "backward() on a non-scalar tensor requires grad_tensors"
                )
            g_data = jnp.ones_like(t._data)
        else:
            g_data = g._data if isinstance(g, Tensor) else jnp.asarray(g)
        if node is None:
            # leaf: accumulate directly
            _collect(t, g_data)
            if accumulate_leaf and not t.stop_gradient:
                t._accumulate_grad(g_data)
            continue
        _collect(t, g_data)
        if node.out_grads is None:
            node.out_grads = [None] * node.n_outputs
        idx = t._out_index
        node.out_grads[idx] = (
            g_data if node.out_grads[idx] is None else node.out_grads[idx] + g_data
        )
        roots.append(node)

    if not roots:
        return

    order = _toposort(roots)

    with no_grad():
        for node in order:
            if node.out_grads is None:
                continue  # not reached by any cotangent
            if node.vjp_fn is None:
                raise RuntimeError(
                    f"backward through {node.name!r} a second time: the graph "
                    "was freed — pass retain_graph=True to the first backward"
                )
            # vjp requires cotangents for all outputs; fill unreached with zeros
            if node.n_outputs == 1:
                in_grads = node.vjp_fn(node.out_grads[0])
            else:
                cts = tuple(
                    c if c is not None
                    else jnp.zeros(av[0], av[1])
                    for c, av in zip(node.out_grads, node.out_avals)
                )
                in_grads = node.vjp_fn(cts)
            for t, g in zip(node.inputs, in_grads):
                if g is None:
                    continue
                _collect(t, g)
                prod = t._grad_node
                if prod is None:
                    if accumulate_leaf and not t.stop_gradient:
                        t._accumulate_grad(g)
                else:
                    if prod.out_grads is None:
                        prod.out_grads = [None] * prod.n_outputs
                    i = t._out_index
                    prod.out_grads[i] = (
                        g if prod.out_grads[i] is None else prod.out_grads[i] + g
                    )
            if not retain_graph:
                node.vjp_fn = None
                node.inputs = ()
            node.out_grads = None
