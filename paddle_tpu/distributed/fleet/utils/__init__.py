"""fleet.utils — parity path for sequence_parallel_utils + hybrid helpers.

Ref: python/paddle/distributed/fleet/utils/ (upstream layout, unverified —
mount empty).
"""
from ..meta_parallel import sequence_parallel as sequence_parallel_utils  # noqa: F401
from ..recompute import recompute  # noqa: F401


def fused_allreduce_gradients(parameter_list, hcg):
    """DP grad sync (ref: fleet/utils/hybrid_parallel_util.py). Under GSPMD
    the psum is emitted inside jitted steps; kept for API parity."""
    return None
