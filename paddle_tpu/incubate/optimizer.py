"""paddle.incubate.optimizer — LookAhead and ModelAverage wrappers (ref:
python/paddle/incubate/optimizer/lookahead.py, modelaverage.py — upstream
layout, unverified — mount empty).

Both wrap an inner optimizer and adjust parameters *after* its jitted
update, with their own state held as jax arrays per parameter — the slow/
averaged copies never enter the inner optimizer's accumulator tree.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead:
    """k-step lookahead (Zhang et al. 2019): every k inner steps the slow
    weights move toward the fast weights, slow += alpha*(fast - slow), and
    the fast weights restart from the slow copy."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha must be in [0, 1]")
        if k < 1:
            raise ValueError("k must be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self._step_count = 0
        self._slow = {}  # id(param) -> slow copy (jax array)

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def step(self):
        params = [p for p in self._parameter_list if p.trainable]
        for p in params:
            if id(p) not in self._slow:
                self._slow[id(p)] = p._data
        self.inner_optimizer.step()
        self._step_count += 1
        if self._step_count % self.k == 0:
            for p in params:
                slow = self._slow[id(p)]
                slow = slow + self.alpha * (p._data - slow)
                self._slow[id(p)] = slow
                p._data = slow

    def clear_grad(self, set_to_zero=True):
        self.inner_optimizer.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        slow = {self._param_name(p): self._slow[id(p)]
                for p in self._parameter_list if id(p) in self._slow}
        return {"inner": self.inner_optimizer.state_dict(),
                "step": self._step_count, "slow": slow}

    def set_state_dict(self, state):
        self.inner_optimizer.set_state_dict(state.get("inner", {}))
        self._step_count = int(state.get("step", 0))
        slow = state.get("slow", {})
        for p in self._parameter_list:
            name = self._param_name(p)
            if name in slow:
                self._slow[id(p)] = jnp.asarray(slow[name])

    def _param_name(self, p):
        return getattr(p, "name", None) or f"param_{id(p)}"

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        # base Optimizer.minimize contract: backward + step, grads kept
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in self._parameter_list]


class ModelAverage:
    """Running average of parameters over a sliding window; `apply()`
    swaps the averaged weights in for evaluation and `restore()` swaps the
    live weights back."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        if parameters is None:
            raise ValueError("parameters is required (pass "
                             "model.parameters())")
        self.average_window_rate = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self._params = list(parameters)
        self._sum = {id(p): jnp.zeros_like(p._data) for p in self._params}
        self._count = 0
        self._backup = None

    def step(self):
        """Accumulate the current weights into the average (call after the
        inner optimizer's step)."""
        window = max(self.min_average_window,
                     min(self.max_average_window,
                         int(self._count * self.average_window_rate) or 1))
        if self._count >= window:
            # restart the window (upstream restores from the current sums)
            for p in self._params:
                self._sum[id(p)] = jnp.zeros_like(p._data)
            self._count = 0
        for p in self._params:
            self._sum[id(p)] = self._sum[id(p)] + p._data
        self._count += 1

    def apply(self, executor=None, need_restore=True):
        """Swap averaged weights in (context-manager style not required)."""
        if self._count == 0:
            return
        if self._backup is not None:
            raise RuntimeError(
                "ModelAverage.apply() called twice without restore(); the "
                "live weights are still backed up — call restore() first")
        self._backup = {id(p): p._data for p in self._params}
        for p in self._params:
            p._data = self._sum[id(p)] / float(self._count)
        if not need_restore:
            self._backup = None

    def restore(self, executor=None):
        if self._backup is None:
            return
        for p in self._params:
            p._data = self._backup[id(p)]
        self._backup = None

    def mean(self, p):
        """Averaged value of one parameter (testing/introspection)."""
        if self._count == 0:
            return np.asarray(p._data)
        return np.asarray(self._sum[id(p)] / float(self._count))
